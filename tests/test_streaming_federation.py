"""Overload-hardened streaming federation (ISSUE 18 satellites): echo
filtering over delta-patched mirrors, absorb-mode occupancy patches,
overcommit-tolerant watch accounting, and pump hygiene.

The regression class pinned here: federated shards mirror the store
through the /backend/v1/ watch, and under protocol v2 MODIFIED events
arrive as field-level deltas applied with ``wire.apply_delta``. The
StreamTrigger's echo rules (bind echo closes the latency loop with no
wake; status-only podgroup write-back must not re-dirty) depend on the
mirror's replace-don't-mutate contract — ``apply_delta`` returning a
*new* object while the handler still holds the old one. If a codec
ever patched in place, every bind echo would look like a no-op update
(old is new) and every podgroup status write like a spec change, and
streaming would either stall the time_to_bind loop or re-dirty the
whole resident world each cycle.
"""

from __future__ import annotations

import dataclasses
import time

import pytest

from kube_batch_tpu import faults
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.apis import wire
from kube_batch_tpu.cache import (
    ClusterStore,
    EventHandler,
    LoopbackBackend,
    SchedulerCache,
)
from kube_batch_tpu.cache.store import PODS, POD_GROUPS
from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.streaming import StreamState, StreamTrigger
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


@pytest.fixture()
def arbiter():
    """A real SchedulerServer as the store process (its own loop idled
    by a scheduler name no workload pod carries)."""
    srv = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    srv.start()
    try:
        yield srv
    finally:
        srv.stop()


# -- delta-codec echo filtering (satellite: v2 patched mirrors) --------------


def test_delta_patched_bind_echo_keeps_old_new_distinct():
    """A v2 bind echo (node_name ""->set as a field delta) applied with
    apply_delta must produce a NEW object so the trigger still sees the
    transition: arrival closed, no wake, no stale degrade."""
    pending = build_pod(name="p0", group_name="g0",
                        req=build_resource_list(cpu=1))
    bound = dataclasses.replace(pending, node_name="n1")
    delta = wire.delta_of(PODS, pending, bound)
    # the hot-path promise: a bind rides as a fraction of the object
    assert "node_name" in delta["changed"] and not delta["removed"]
    patched = wire.apply_delta(PODS, pending, delta)
    assert patched is not pending, "apply_delta must copy, not mutate"
    assert pending.node_name == "" and patched.node_name == "n1"

    trig = StreamTrigger(absorb_external=True)
    uid = pending.metadata.uid
    trig._on_event(PODS, uid, pending, None)
    assert trig.backlog_pods() == 1
    trig.drain()
    # the echo, exactly as _apply_events hands it over: (old, patched)
    trig._on_event(PODS, uid, patched, pending)
    assert trig.backlog_pods() == 0, "bind echo must close the arrival"
    assert not trig.wait(0), "bind echo must not wake the loop"
    work = trig.drain()
    assert not work.stale and not work.bound_patches


def test_delta_patched_podgroup_status_echo_not_redirtied():
    """close_session's status-only podgroup write-back, round-tripped
    through the v2 delta codec, must keep spec equality so the trigger
    skips it — and a real spec change through the same codec must not."""
    from kube_batch_tpu.apis.types import PodGroupPhase

    pg = build_pod_group("g1", min_member=3)
    status2 = dataclasses.replace(
        pg, status=dataclasses.replace(pg.status, phase=PodGroupPhase.RUNNING)
    )
    patched = wire.apply_delta(POD_GROUPS, pg, wire.delta_of(POD_GROUPS, pg, status2))
    assert patched is not pg
    assert patched.spec == pg.spec, "status delta must not disturb spec"

    trig = StreamTrigger()
    trig._on_event(POD_GROUPS, "default/g1", patched, pg)
    assert not trig.wait(0) and trig.drain().gangs == set()

    spec2 = dataclasses.replace(
        pg, spec=dataclasses.replace(pg.spec, min_member=5)
    )
    patched2 = wire.apply_delta(POD_GROUPS, pg, wire.delta_of(POD_GROUPS, pg, spec2))
    assert patched2.spec.min_member == 5
    trig._on_event(POD_GROUPS, "default/g1", patched2, pg)
    assert trig.wait(0) and trig.drain().gangs == {"default/g1"}


# -- absorb mode (federated streaming) ---------------------------------------


def test_absorb_mode_turns_peer_churn_into_patches_not_degrade():
    """A peer shard's bind crosses the federated filter as a bound-pod
    ADD (no wake: consumed capacity admits nothing) and its release as
    a DELETE (wake: freed capacity can admit the backlog). Without
    absorb mode both degrade to a stale full cycle."""
    peer = build_pod(name="peer-0", node_name="n2",
                     req=build_resource_list(cpu=1))
    key = peer.metadata.uid

    trig = StreamTrigger(absorb_external=True)
    trig._on_event(PODS, key, peer, None)
    assert not trig.wait(0), "peer bind must not wake the loop"
    work = trig.drain()
    assert work.bound_patches == [("add", key, peer)] and not work.stale

    trig._on_event(PODS, key, None, peer)
    assert trig.wait(0), "peer release frees capacity: wake"
    work = trig.drain()
    assert work.bound_patches == [("remove", key, peer)] and not work.stale

    # contrast: a solo (non-federated) trigger treats both as stale
    solo = StreamTrigger()
    solo._on_event(PODS, key, peer, None)
    work = solo.drain()
    assert work.stale and "appeared outside a cycle" in work.stale_reason


def _resident(cpu: int = 4) -> tuple[StreamState, NodeInfo]:
    ni = NodeInfo(build_node(
        "n0", build_resource_list(cpu=cpu, memory=f"{cpu}Gi", pods=16)
    ))

    class _Session:
        nodes = {"n0": ni}

    st = StreamState()
    st.adopt_full_cycle(_Session())
    return st, ni


def test_apply_bound_patches_absorbs_and_skips_duplicates():
    st, ni = _resident(cpu=4)
    peer = build_pod(name="peer-1", node_name="n0",
                     req=build_resource_list(cpu=1, memory="512Mi"))
    idle0 = ni.idle.milli_cpu
    assert st.apply_bound_patches([("add", "k", peer)]) is True
    assert ni.idle.milli_cpu == idle0 - 1000 and len(ni.tasks) == 1
    # duplicate add: the adopted snapshot beat the patch — benign skip
    assert st.apply_bound_patches([("add", "k", peer)]) is True
    assert len(ni.tasks) == 1 and st.valid
    assert st.apply_bound_patches([("remove", "k", peer)]) is True
    assert ni.idle.milli_cpu == idle0 and not ni.tasks
    # duplicate remove: already gone — benign skip, still valid
    assert st.apply_bound_patches([("remove", "k", peer)]) is True
    assert st.valid


def test_apply_bound_patches_invalidates_on_true_divergence():
    # unknown node: the resident table genuinely diverged
    st, _ = _resident()
    ghost = build_pod(name="g", node_name="nowhere",
                      req=build_resource_list(cpu=1))
    assert st.apply_bound_patches([("add", "k", ghost)]) is False
    assert not st.valid and "not resident" in st.reason

    # resource underflow: the absorb path keeps the strict accounting
    # raise (unlike the cache's watch path) — degrade to a full rebuild
    st, _ = _resident(cpu=1)
    fat = build_pod(name="fat", node_name="n0",
                    req=build_resource_list(cpu=2))
    assert st.apply_bound_patches([("add", "k", fat)]) is False
    assert not st.valid


# -- overcommit-tolerant watch accounting ------------------------------------


def test_watch_delivered_bind_race_records_negative_idle_and_heals():
    """Two shards race binds onto one node; the loser's cache receives
    both as watch facts. The mirror must record the overcommit (idle
    goes negative — unfit to every admission check) instead of killing
    the pump, and a per-cycle clone of the oversubscribed node must not
    abort the cycle. Deleting one pod heals the accounting exactly."""
    store = ClusterStore()
    store.create_queue(build_queue("default"))
    store.create_node(build_node("tiny", build_resource_list(
        cpu=1, memory="1Gi", pods=8)))
    cache = SchedulerCache(store)
    for i in range(2):
        store.create_pod(build_pod(
            name=f"winner-{i}", node_name="tiny",
            req=build_resource_list(cpu=1, memory="512Mi"),
        ))
    with cache._mutex:
        ni = cache.nodes["tiny"]
        assert len(ni.tasks) == 2, "both committed binds must be resident"
        assert ni.idle.milli_cpu == -1000, "overcommit must read as negative idle"
        clone = ni.clone()  # the cycle snapshot must survive the replay
        assert clone.idle.milli_cpu == -1000
    store.delete_pod("default", "winner-1")
    with cache._mutex:
        ni = cache.nodes["tiny"]
        assert len(ni.tasks) == 1 and ni.idle.milli_cpu == 0


# -- pump hygiene (satellite: shutdown + handler survival) -------------------


def test_backend_pump_thread_shutdown_hygiene(arbiter):
    """start() spawns exactly one kb-backend thread; stop() joins it
    and clears the handle; both are idempotent. A leaked pump thread
    keeps long-polling a dead arbiter forever."""
    backend = LoopbackBackend(f"http://127.0.0.1:{arbiter.listen_port}")
    seen: list[str] = []
    backend.add_event_handler(
        PODS, EventHandler(on_add=lambda obj: seen.append(obj.name))
    )
    backend.start(period=0.02)
    t = backend._thread
    assert t is not None and t.is_alive()
    backend.start(period=0.02)
    assert backend._thread is t, "double start must not spawn a second pump"
    arbiter.store.create_pod(build_pod(name="live", req=build_resource_list(cpu=1)))
    deadline = time.monotonic() + 5.0
    while "live" not in seen and time.monotonic() < deadline:
        time.sleep(0.005)
    assert "live" in seen
    backend.stop()
    assert backend._thread is None and not t.is_alive()
    backend.stop()  # idempotent
    assert backend._thread is None


def test_trigger_attach_detach_restores_listener_count():
    from kube_batch_tpu.ops import encode_cache

    before = encode_cache.listener_count()
    trig = StreamTrigger()
    trig.attach()
    assert encode_cache.listener_count() == before + 1
    trig.detach()
    assert encode_cache.listener_count() == before
    trig.detach()  # idempotent
    assert encode_cache.listener_count() == before


def test_bad_handler_does_not_kill_the_pump(arbiter):
    """One handler raising on an event must not stall the watch for
    every other subscriber (the pump is shared infrastructure): later
    handlers still run, the batch still counts, later pumps still
    deliver."""
    backend = LoopbackBackend(f"http://127.0.0.1:{arbiter.listen_port}")

    def explode(obj):
        raise ValueError(f"poison object {obj.name}")

    seen: list[str] = []
    backend.add_event_handler(PODS, EventHandler(on_add=explode))
    backend.add_event_handler(
        PODS, EventHandler(on_add=lambda obj: seen.append(obj.name))
    )
    arbiter.store.create_pod(build_pod(name="a", req=build_resource_list(cpu=1)))
    assert backend.pump() >= 1
    assert seen == ["a"], "the handler after the poisoned one must still run"
    arbiter.store.create_pod(build_pod(name="b", req=build_resource_list(cpu=1)))
    assert backend.pump() >= 1
    assert seen == ["a", "b"], "the pump must survive to the next round"


def test_stream_pump_fault_skips_rounds_then_redelivers(arbiter):
    """An armed ``stream.pump`` drops whole rounds (mirror ages, no
    partial batches); once exhausted, the unadvanced cursor redelivers
    everything exactly once."""
    backend = LoopbackBackend(f"http://127.0.0.1:{arbiter.listen_port}")
    seen: list[str] = []
    backend.add_event_handler(
        PODS, EventHandler(on_add=lambda obj: seen.append(obj.name))
    )
    arbiter.store.create_pod(build_pod(name="held", req=build_resource_list(cpu=1)))
    faults.registry.arm("stream.pump", count=2)
    assert backend.pump() == 0 and backend.pump() == 0
    assert seen == [], "a dropped round must not leak a partial batch"
    assert backend.pump() >= 1
    assert seen == ["held"], "exhausted fault must redeliver exactly once"
