"""Tier-1 tests for the concurrency sanitizer
(kube_batch_tpu.analysis.threads) and its runtime half, the
happens-before RaceWitness (kube_batch_tpu.utils.race).

Each KBT-T code is proven on a seeded-violation fixture — source with
exactly the defect class the check exists to catch — plus its negative
twin (the disciplined spelling must NOT fire). The RaceWitness drills
exercise the vector-clock edges directly: two critical sections on one
lock are ordered, start/join orders parent and child, and a true race
is caught with a deterministic trace id that replays bit-identically.
The live tree runs as a smoke: the analyzer under the committed
baseline must be clean, and the witness drive over the real
streaming-federation bind path must report zero conflicts.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys
import threading

import pytest

from kube_batch_tpu.analysis import (
    SourceFile,
    apply_baseline,
    load_baseline,
    load_tree,
)
from kube_batch_tpu.analysis import threads
from kube_batch_tpu.utils.race import RaceWitness

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sf(path: str, source: str) -> SourceFile:
    return SourceFile(path, source, ast.parse(source, path))


# -- seeded fixtures: every code fires, every negative twin stays silent ------


@pytest.mark.parametrize("name", sorted(threads.FIXTURES))
def test_fixture_matches_seeded_expectations(name):
    source = threads.FIXTURES[name]
    got = {(f.code, f.line) for f in threads.analyze([sf(f"fixture:{name}", source)])}
    want = threads._expected(source)
    assert got == want, f"{name}: expected {sorted(want)} got {sorted(got)}"
    if name.endswith("_pos"):
        # a positive fixture that seeds nothing proves nothing
        assert want, f"{name} seeds no # VIOLATION: markers"
        code = "KBT-" + name.split("_")[0].upper()
        assert {c for c, _ in want} == {code}
    else:
        assert not want


def test_selfcheck_is_clean():
    assert threads.selfcheck() == []


def test_t001_noqa_suppresses():
    # the positive fixture with per-line waivers goes quiet
    source = "\n".join(
        line + "  # noqa: KBT-T001" if "# VIOLATION:" in line else line
        for line in threads.FIXTURES["t001_pos"].splitlines()
    )
    assert threads.analyze([sf("fixture:t001_noqa", source)]) == []


# -- RaceWitness: the vector-clock edges, exercised directly ------------------


class Box:
    def __init__(self) -> None:
        self.field = 0


def test_witness_lock_ordered_accesses_are_clean():
    w = RaceWitness()
    box = w.watch(Box(), ["field"])
    mu = w.wrap("box.mu", threading.Lock())
    first = threading.Event()

    def a() -> None:
        with mu:
            box.field = 1
        first.set()

    def b() -> None:
        first.wait(5.0)
        with mu:
            box.field = 2

    ta, tb = w.spawn(a, name="lock-a"), w.spawn(b, name="lock-b")
    ta.start(), tb.start()
    ta.join(5.0), tb.join(5.0)
    assert w.reports == []
    w.assert_clean()


def test_witness_join_ordered_accesses_are_clean():
    w = RaceWitness()
    box = w.watch(Box(), ["field"])

    def child() -> None:
        box.field = 1

    t = w.spawn(child, name="join-child")
    t.start()
    t.join(5.0)
    box.field = 2  # ordered by the join edge, no lock needed
    assert w.reports == []


def test_witness_catches_true_race_with_deterministic_trace_id():
    def race_once() -> list:
        w = RaceWitness()
        box = w.watch(Box(), ["field"])
        first = threading.Event()

        def a() -> None:
            box.field = 1
            first.set()

        def b() -> None:
            first.wait(5.0)  # an Event is NOT a happens-before edge
            box.field = 2

        ta, tb = w.spawn(a, name="race-a"), w.spawn(b, name="race-b")
        ta.start(), tb.start()
        ta.join(5.0), tb.join(5.0)
        return list(w.reports)

    r1 = race_once()
    assert r1, "unordered cross-thread writes must be reported"
    assert "[trace Box.field:0-1]" in r1[0]
    # same drive, same seq numbers, same report text: replayable
    assert race_once() == r1


def test_witness_selfcheck_is_clean():
    assert threads.witness_selfcheck() == []


# -- live smokes --------------------------------------------------------------


def test_witness_drive_over_streaming_bind_path_is_clean():
    res = threads.witness_drive(writers=2, events_per_writer=20)
    assert res["ok"], res["reports"] or res["leaked"]
    assert res["accesses"] > 0, "the drive must actually touch watched fields"
    assert res["leaked"] == []


def test_live_tree_is_clean_under_committed_baseline():
    findings = threads.analyze(load_tree(REPO))
    bl = load_baseline(os.path.join(REPO, "hack", "lint-baseline.toml"), REPO)
    assert bl.errors == [], [e.message for e in bl.errors]
    kept, _suppressed, _stale = apply_baseline(findings, bl)
    kept = [f for f in kept if f.code.startswith("KBT-T")]
    assert kept == [], "unsuppressed findings:\n" + "\n".join(
        f.render() for f in kept
    )


def test_cli_json_clean_exit():
    proc = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis.threads", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["ok"] is True
    assert summary["selfcheck"]["static"] == []
    assert summary["selfcheck"]["witness"] == []
