"""Admission control plane (overload-hardening tentpole): per-tenant
lanes, the hysteresis-banded brownout controller, and the server's
front-door 429 path.

The invariants driven here:

- **bounded, never silent**: every shed carries a positive
  ``Retry-After`` hint and a typed reason; nothing is dropped quietly;
- **ladder discipline**: escalation halves then defers the lowest
  tier first and never touches the protected (top) tier; recovery
  retraces with a longer dwell so the loop cannot flap inside the
  hysteresis band;
- **conservative on partial data**: a dark shard
  (``fleet_shard_up=0``) holds the current brownout level instead of
  reading silence as health;
- **fail-static**: a dead controller tick (``admission.controller``
  fault) leaves the last good lane factors in force.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from kube_batch_tpu import admission, faults
from kube_batch_tpu.admission import (
    AdmissionGate,
    BackpressureController,
    LaneSpec,
    TokenBucket,
    parse_lane_specs,
)
from kube_batch_tpu.server import SchedulerServer


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    yield
    faults.registry.reset()


@pytest.fixture(autouse=True)
def _clean_gate(monkeypatch):
    """No test leaves the module-level gate armed."""
    monkeypatch.delenv(admission.ENV, raising=False)
    yield
    admission.configure("")


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- token bucket -------------------------------------------------------------


def test_token_bucket_burst_then_rate():
    clock = FakeClock()
    bucket = TokenBucket(rate=2.0, burst=4.0, clock=clock)
    assert [bucket.take() for _ in range(4)] == [True] * 4
    assert not bucket.take()  # burst exhausted, no time passed
    assert bucket.retry_after() > 0
    clock.advance(0.5)  # one token accrues at 2/s
    assert bucket.take()
    assert not bucket.take()


def test_token_bucket_closed_lane():
    clock = FakeClock()
    bucket = TokenBucket(rate=0.0, burst=10.0, clock=clock)
    assert not bucket.take()
    assert bucket.retry_after() == 1.0  # fixed hint, no division by zero
    clock.advance(1000.0)
    assert not bucket.take()  # closed stays closed regardless of time


def test_token_bucket_set_rate_settles_accrual_first():
    clock = FakeClock()
    bucket = TokenBucket(rate=10.0, burst=10.0, clock=clock)
    for _ in range(10):
        assert bucket.take()
    clock.advance(0.5)  # 5 tokens accrued at the OLD rate
    bucket.set_rate(1.0)
    taken = sum(1 for _ in range(10) if bucket.take())
    assert taken == 5  # old-rate accrual honored, new rate applies after


# -- lane spec parsing --------------------------------------------------------


def test_parse_lane_specs_full_and_fallbacks():
    specs = parse_lane_specs("high:100:20:40:300,batch:10,junk:x:y,high:1")
    by_name = {s.name: s for s in specs}
    assert by_name["high"] == LaneSpec("high", 100, 20.0, 40.0, 300)
    assert by_name["batch"].priority == 10
    # malformed numeric fields fall back instead of disabling admission
    assert by_name["junk"].priority == 0
    # duplicate lane names keep the first definition
    assert by_name["high"].priority == 100
    # the catch-all lane is auto-added at the lowest declared priority
    assert by_name["default"].priority == 0


def test_parse_lane_specs_keeps_explicit_default():
    specs = parse_lane_specs("high:100,default:50")
    by_name = {s.name: s for s in specs}
    assert by_name["default"].priority == 50
    assert len(specs) == 2


# -- brownout ladder ----------------------------------------------------------


def _specs():
    return parse_lane_specs("high:100,batch:10,low:0")


def _payload(p99=0.0, backlog=0.0, shard_up=None, conflicts=None):
    return {
        "slo": {"time_to_bind": {"high": {"n": 10, "p99": p99}}},
        "backlog_pods": backlog,
        "shard_up": shard_up if shard_up is not None else {"s0": True},
        "node_conflict_topk": conflicts or {},
    }


def test_ladder_escalates_lowest_tier_first_and_protects_top():
    ctl = BackpressureController(_specs(), slo_s=1.0, band=0.2)
    assert ctl.max_level == 4  # two rungs each for priorities 0 and 10
    hot = _payload(p99=5.0)
    for _ in range(2 * ctl.UP_TICKS):
        ctl.tick(hot, watch_age=0.0)
    assert ctl.level == 2
    assert ctl.factor_for(0) == admission.min_rate_factor()  # low: deferred
    assert ctl.factor_for(10) == 1.0                         # batch: untouched yet
    assert ctl.factor_for(100) == 1.0                        # protected
    for _ in range(2 * ctl.UP_TICKS):
        ctl.tick(hot, watch_age=0.0)
    assert ctl.level == 4
    assert ctl.factor_for(10) == admission.min_rate_factor()
    assert ctl.factor_for(100) == 1.0  # the top tier is never deferred
    # saturation: more pressure cannot push past max_level
    for _ in range(4):
        ctl.tick(hot, watch_age=0.0)
    assert ctl.level == 4


def test_ladder_recovery_needs_long_dwell_and_no_flap_in_band():
    ctl = BackpressureController(_specs(), slo_s=1.0, band=0.2)
    for _ in range(ctl.UP_TICKS):
        ctl.tick(_payload(p99=5.0), watch_age=0.0)
    assert ctl.level == 1
    # inside the hysteresis band: neither direction moves
    for _ in range(20):
        assert ctl.tick(_payload(p99=1.0), watch_age=0.0) == "steady"
    assert ctl.level == 1
    # below band: recovery only after DOWN_TICKS consecutive calm ticks
    for i in range(ctl.DOWN_TICKS - 1):
        assert ctl.tick(_payload(p99=0.1), watch_age=0.0) == "steady"
    assert ctl.level == 1
    assert ctl.tick(_payload(p99=0.1), watch_age=0.0) == "recover"
    assert ctl.level == 0


def test_dark_shard_blocks_recovery():
    ctl = BackpressureController(_specs(), slo_s=1.0, band=0.2)
    for _ in range(ctl.UP_TICKS):
        ctl.tick(_payload(p99=5.0), watch_age=0.0)
    assert ctl.level == 1
    dark = _payload(p99=0.1, shard_up={"s0": True, "s1": False})
    for _ in range(5 * ctl.DOWN_TICKS):
        assert ctl.tick(dark, watch_age=0.0) == "dark"
    assert ctl.level == 1  # silence is not health
    # the shard comes back: recovery resumes normally
    for _ in range(ctl.DOWN_TICKS):
        ctl.tick(_payload(p99=0.1), watch_age=0.0)
    assert ctl.level == 0


def test_pressure_is_worst_of_all_signals():
    ctl = BackpressureController(_specs(), slo_s=10.0, band=0.2,
                                 backlog_budget=100.0)
    ctl.tick(_payload(p99=1.0), watch_age=50.0)  # stale watch alone
    assert ctl.pressure == pytest.approx(5.0)
    ctl.tick(_payload(p99=1.0, conflicts={"n0": 500}), watch_age=0.0)
    assert ctl.pressure == pytest.approx(10.0)
    ctl.tick(_payload(p99=1.0, backlog=250.0), watch_age=0.0)
    assert ctl.pressure == pytest.approx(2.5)


def test_controller_fault_is_fail_static():
    gate = AdmissionGate(_specs(), clock=FakeClock(),
                         fleet_fn=lambda: _payload(p99=50.0),
                         age_fn=lambda: 0.0, slo_s=1.0, interval_s=0.0)
    clock = gate._clock
    for _ in range(gate.controller.UP_TICKS):
        clock.advance(1.0)
        gate.maybe_tick()
    level = gate.controller.level
    assert level >= 1
    factors = {n: l.factor for n, l in gate.lanes.items()}
    faults.registry.arm("admission.controller", count=3)
    for _ in range(3):
        clock.advance(1.0)
        gate.maybe_tick()
    assert gate.controller.last_outcome == "fault"
    assert gate.controller.level == level  # ladder frozen
    assert {n: l.factor for n, l in gate.lanes.items()} == factors


# -- the gate -----------------------------------------------------------------


def _quiet_gate(spec="high:100:5:5:3,low:0:5:5:3", **kwargs):
    clock = FakeClock()
    gate = AdmissionGate(
        parse_lane_specs(spec), clock=clock,
        fleet_fn=lambda: _payload(p99=0.0), age_fn=lambda: 0.0,
        slo_s=30.0, interval_s=1.0, **kwargs,
    )
    return gate, clock


def test_gate_admits_charges_and_credits_backlog():
    gate, _clock = _quiet_gate()
    for i in range(3):
        d = gate.decide("high", key=f"default/p{i}")
        assert d.admitted and d.reason == "admitted" and d.lane == "high"
    d = gate.decide("high", key="default/p3")
    assert not d.admitted and d.reason == "shed_backlog"
    assert d.retry_after_s > 0
    gate.note_done("default/p0")  # a bind credits the lane
    assert gate.decide("high", key="default/p4").admitted
    # double-credit of the same key is a no-op
    gate.note_done("default/p0")
    gate.note_done("default/p0")
    assert gate.lanes["high"].inflight == 3


def test_gate_rate_shed_carries_retry_after():
    gate, clock = _quiet_gate(spec="high:100:2:2:100")
    assert gate.decide("high").admitted
    assert gate.decide("high").admitted
    d = gate.decide("high")
    assert not d.admitted and d.reason == "shed_rate" and d.retry_after_s > 0
    clock.advance(1.0)  # 2/s refills two tokens
    assert gate.decide("high").admitted


def test_gate_unknown_queue_lands_in_default_lane():
    gate, _clock = _quiet_gate()
    d = gate.decide("no-such-queue", key="default/x")
    assert d.admitted and d.lane == "default"


def test_gate_brownout_defers_low_lane_only():
    clock = FakeClock()
    gate = AdmissionGate(
        parse_lane_specs("high:100:50:50:100,low:0:50:50:100"),
        clock=clock, fleet_fn=lambda: _payload(p99=500.0),
        age_fn=lambda: 0.0, slo_s=1.0, interval_s=1.0,
    )
    for _ in range(2 * gate.controller.UP_TICKS):
        clock.advance(1.0)
        gate.maybe_tick()
    assert gate.controller.level >= 2
    d = gate.decide("low")
    assert not d.admitted and d.reason == "shed_brownout"
    assert d.retry_after_s >= 1.0
    assert gate.decide("high").admitted  # protected lane still open


def test_gate_shed_fault_point():
    gate, _clock = _quiet_gate()
    faults.registry.arm("admission.shed", count=1)
    d = gate.decide("high", key="default/f0")
    assert not d.admitted and d.reason == "shed_fault" and d.retry_after_s > 0
    # the fault fired AFTER the bucket take but the admit was not charged
    assert gate.lanes["high"].inflight == 0
    assert gate.decide("high", key="default/f1").admitted


def test_configure_on_words_and_off_words(monkeypatch):
    monkeypatch.setenv(admission.ENV, "on")
    assert admission.configure()
    gate = admission.active()
    assert set(gate.lanes) == {"high", "batch", "default"}
    monkeypatch.setenv(admission.ENV, "off")
    assert not admission.configure()
    assert admission.debug_payload() == admission.NOOP_PAYLOAD


# -- the server front door ----------------------------------------------------


def _post(port: str, path: str, body: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def test_server_front_door_429_and_debug_endpoint(monkeypatch, tmp_path):
    monkeypatch.setenv(admission.ENV, "high:100:2:2:100,default:0:2:2:100")
    srv = SchedulerServer(
        scheduler_name="adm-test", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    srv.start()
    try:
        port = srv.listen_port
        codes = []
        for i in range(4):
            code, headers, body = _post(
                port, "/apis/v1alpha1/pods",
                {"name": f"adm-{i}", "requests": {"cpu": "1"}},
            )
            codes.append(code)
            if code == 429:
                payload = json.loads(body)
                assert payload["reason"] in ("shed_rate", "shed_backlog")
                assert payload["retry_after_s"] > 0
                assert int(headers["Retry-After"]) >= 1
        assert codes.count(201) == 2  # burst of 2 on the default lane
        assert codes.count(429) == 2
        status, _h, body = _post_get(port, "/debug/admission")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        lanes = payload["lanes"]
        assert lanes["default"]["admitted"] == 2
        assert lanes["default"]["shed"].get("shed_rate", 0) == 2
        assert lanes["default"]["inflight"] == 2
        # a deleted pending pod credits the lane backlog (note_done)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/apis/v1alpha1/pods/default/adm-0",
            method="DELETE",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            _s, _h, body = _post_get(port, "/debug/admission")
            if json.loads(body)["lanes"]["default"]["inflight"] == 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("pod delete never credited the lane")
    finally:
        srv.stop()


def _post_get(port: str, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def test_server_without_admission_is_a_noop(monkeypatch):
    monkeypatch.delenv(admission.ENV, raising=False)
    srv = SchedulerServer(
        scheduler_name="adm-off", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    srv.start()
    try:
        port = srv.listen_port
        for i in range(10):
            code, _h, _b = _post(
                port, "/apis/v1alpha1/pods",
                {"name": f"free-{i}", "requests": {"cpu": "1"}},
            )
            assert code == 201
        status, _h, body = _post_get(port, "/debug/admission")
        assert status == 200
        assert json.loads(body) == {"enabled": False}
    finally:
        srv.stop()
