"""ieee_div: correctly-rounded division on sloppy-divide backends.

The serial oracle divides with CPython's IEEE-754 semantics; some XLA
backends lower division to a reciprocal-multiply that lands 1+ ulp off
(measured on the TPU build this repo benches on), which flipped
proportion share ties and least-requested floor boundaries in the
device kernels (ops/kernels.py ieee_div docstring). These tests pin
the fix: kernel division must reproduce numpy's quotient bit-for-bit
in the dtype it runs in."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_batch_tpu.ops.kernels import ieee_div  # noqa: E402


def test_f32_division_bit_exact_on_default_backend():
    rng = np.random.default_rng(7)
    x = rng.uniform(1e-3, 1e9, 100_000).astype(np.float32)
    y = rng.uniform(1e-3, 1e9, 100_000).astype(np.float32)
    got = np.asarray(jax.jit(ieee_div)(x, y))
    np.testing.assert_array_equal(got, x / y)


def test_least_requested_floor_boundaries():
    """floor((cap-req)*10/cap): an empty node must score exactly 10 —
    the plain backend divide returned 9.99… and floored to 9."""
    rng = np.random.default_rng(0)
    cap = rng.integers(1000, 256_000, 50_000).astype(np.float32)
    req = (cap * rng.random(50_000).astype(np.float32)).astype(np.int64).astype(
        np.float32
    )
    f = jax.jit(lambda v, c: jnp.floor(ieee_div(v * 10.0, c)))
    got = np.asarray(f(cap - req, cap))
    want = np.floor((cap - req) * np.float32(10.0) / cap)
    np.testing.assert_array_equal(got, want)
    # the empty-node case specifically
    empty = np.asarray(f(cap, cap))
    assert (empty == 10.0).all()


def test_f64_division_bit_exact_on_cpu_backend():
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        pytest.skip("no cpu backend")
    rng = np.random.default_rng(1)
    x = rng.uniform(1e-3, 1e12, 100_000)
    y = rng.uniform(1e-3, 1e12, 100_000)
    from kube_batch_tpu.testing import x64_enabled

    with jax.default_device(cpu):
        with x64_enabled():
            got = np.asarray(jax.jit(ieee_div)(x, y))
    np.testing.assert_array_equal(got, x / y)


def test_f32_division_bit_exact_on_tpu_backend():
    """The backend the drift was measured on. Under the suite's conftest
    (platform pinned to cpu) the TPU may be uninitializable — skip then;
    the bench/driver path still exercises it for real."""
    try:
        tpu = jax.devices("tpu")[0]
    except Exception:  # noqa: BLE001 -- platform pinned or absent
        pytest.skip("tpu backend unavailable under this test config")
    rng = np.random.default_rng(3)
    x = rng.uniform(1e-3, 1e9, 50_000).astype(np.float32)
    y = rng.uniform(1e-3, 1e9, 50_000).astype(np.float32)
    with jax.default_device(tpu):
        got = np.asarray(jax.jit(ieee_div)(x, y))
    np.testing.assert_array_equal(got, x / y)


def test_share_tie_preserved_in_f32():
    """Two queues whose f64 shares differ by 1 ulp collapse to the same
    f32 — the kernel must then tie-break by rank, and ieee_div must not
    reorder them (regression shape from the multi_tenant_ml case)."""
    d1, d2 = np.float32(6651.8848), np.float32(4434.5898)
    a1, a2 = np.float32(6000.0), np.float32(4000.0)
    s1 = float(jax.jit(ieee_div)(a1, d1))
    s2 = float(jax.jit(ieee_div)(a2, d2))
    assert s1 == np.float32(a1) / np.float32(d1)
    assert s2 == np.float32(a2) / np.float32(d2)
