"""Resource arithmetic invariants — port of the reference table tests
(reference pkg/scheduler/api/resource_info_test.go)."""

import pytest

from kube_batch_tpu.api import Resource
from kube_batch_tpu.api.resource_info import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
)


def res(mcpu=0.0, mem=0.0, scalars=None):
    return Resource(milli_cpu=mcpu, memory=mem, scalars=scalars)


class TestConstruction:
    def test_from_resource_list_converts_units(self):
        r = Resource.from_resource_list(
            {"cpu": 2, "memory": 3 * 2**30, "pods": 10, "nvidia.com/gpu": 1}
        )
        assert r.milli_cpu == 2000
        assert r.memory == 3 * 2**30
        assert r.max_task_num == 10
        assert r.scalars["nvidia.com/gpu"] == 1000

    def test_empty_and_none(self):
        assert Resource.from_resource_list(None) == Resource.empty()
        assert Resource.from_resource_list({}).is_empty()

    def test_clone_is_deep(self):
        r = res(1000, 100, {"nvidia.com/gpu": 1000})
        c = r.clone()
        c.add(res(1, 1, {"nvidia.com/gpu": 1}))
        assert r == res(1000, 100, {"nvidia.com/gpu": 1000})
        assert c != r


class TestPredicates:
    # reference resource_info_test.go IsEmpty cases
    @pytest.mark.parametrize(
        "r,expected",
        [
            (res(), True),
            (res(MIN_MILLI_CPU - 1, MIN_MEMORY - 1), True),
            (res(MIN_MILLI_CPU, 0), False),
            (res(0, MIN_MEMORY), False),
            (res(0, 0, {"nvidia.com/gpu": MIN_MILLI_SCALAR}), False),
            (res(0, 0, {"nvidia.com/gpu": MIN_MILLI_SCALAR - 1}), True),
        ],
    )
    def test_is_empty(self, r, expected):
        assert r.is_empty() is expected

    def test_is_zero(self):
        r = res(5, 5, {"nvidia.com/gpu": 5})
        assert r.is_zero("cpu")
        assert r.is_zero("memory")
        assert r.is_zero("nvidia.com/gpu")
        with pytest.raises(KeyError):
            r.is_zero("google.com/tpu")
        assert Resource.empty().is_zero("whatever")  # no scalars at all -> zero

    @pytest.mark.parametrize(
        "l,r,expected",
        [
            # Go nil-map parity ({} == nil, resource_info.go:234-239):
            # both scalar-free -> False even when cpu/mem strictly less.
            # This quirk gates preempt.validateVictims (preempt.go:268),
            # reclaim (reclaim.go:156) and enqueue's brake (enqueue.go:88).
            (res(100, 100), res(200, 200), False),
            (res(100, 100), res(100, 200), False),  # not strictly less on cpu
            # Left nil, right has scalars -> True (resource_info.go:235-240).
            (res(100, 100), res(200, 200, {"g": 2}), True),
            (res(100, 100, {"g": 1}), res(200, 200, {"g": 2}), True),
            (res(100, 100, {"g": 2}), res(200, 200, {"g": 2}), False),
            (res(100, 100, {"g": 1}), res(200, 200), False),  # scalar missing on r
        ],
    )
    def test_less(self, l, r, expected):
        assert l.less(r) is expected

    def test_less_policy_call_sites(self):
        """The nil-map quirk at its policy call sites: a victim set whose
        aggregate resreq is scalar-free never fails preempt's
        validateVictims 'not enough resources' check, exactly like Go."""
        victims_total = res(500, 500)  # cpu/mem only
        resreq = res(1000, 1000)
        assert victims_total.less(resreq) is False  # Go: both nil -> False
        # With scalars on both sides the check becomes meaningful again.
        assert res(500, 500, {"g": 1}).less(res(1000, 1000, {"g": 2})) is True

    @pytest.mark.parametrize(
        "l,r,expected",
        [
            (res(100, 100), res(100, 100), True),  # equal within epsilon
            (res(100 + MIN_MILLI_CPU - 1, 100), res(100, 100), True),
            (res(100 + MIN_MILLI_CPU, 100), res(100, 100), False),
            (res(0, 100 + MIN_MEMORY), res(0, 100), False),
            # Go nil-map parity (resource_info.go:264-267): any scalar
            # entry on the left vs no scalars at all on the right -> False,
            # even within epsilon of zero.
            (res(0, 0, {"g": 5}), res(0, 0), False),
            (res(0, 0, {"g": 5}), res(0, 0, {"h": 1}), True),  # epsilon vs present map
            (res(0, 0, {"g": MIN_MILLI_SCALAR}), res(0, 0), False),
        ],
    )
    def test_less_equal_epsilon(self, l, r, expected):
        assert l.less_equal(r) is expected


class TestArithmetic:
    def test_add(self):
        r = res(100, 100, {"g": 1}).add(res(50, 50, {"g": 1, "t": 2}))
        assert r == res(150, 150, {"g": 2, "t": 2})

    def test_sub(self):
        r = res(100, 100, {"g": 2}).sub(res(50, 50, {"g": 1}))
        assert r == res(50, 50, {"g": 1})

    def test_sub_underflow_raises(self):
        with pytest.raises(ValueError):
            res(100, 100).sub(res(200, 100))

    def test_sub_within_epsilon_allowed(self):
        # LessEqual is epsilon-tolerant, so sub can leave tiny negatives.
        r = res(100, 100).sub(res(100 + MIN_MILLI_CPU / 2, 100))
        assert r.milli_cpu == pytest.approx(-MIN_MILLI_CPU / 2)

    def test_set_max_resource(self):
        r = res(100, 300, {"g": 1})
        r.set_max_resource(res(200, 200, {"g": 0.5, "t": 4}))
        assert r == res(200, 300, {"g": 1, "t": 4})

    def test_fit_delta_epsilon_margin(self):
        r = res(100, 100).fit_delta(res(100, 0))
        assert r.milli_cpu == -MIN_MILLI_CPU  # 100 - (100 + eps)
        assert r.memory == 100  # memory not requested -> untouched

    def test_fit_delta_scalar(self):
        r = res(0, 0, {"g": 500}).fit_delta(res(0, 0, {"g": 1000}))
        assert r.scalars["g"] == 500 - 1000 - MIN_MILLI_SCALAR

    def test_multi(self):
        assert res(100, 100, {"g": 3}).multi(2) == res(200, 200, {"g": 6})

    def test_max_task_num_excluded_from_arithmetic(self):
        a = Resource.from_resource_list({"pods": 10, "cpu": 1})
        b = Resource.from_resource_list({"pods": 20, "cpu": 1})
        a.add(b)
        assert a.max_task_num == 10  # untouched by Add (resource_info.go:38-39)


class TestAccess:
    def test_get(self):
        r = res(100, 200, {"g": 3})
        assert r.get("cpu") == 100
        assert r.get("memory") == 200
        assert r.get("g") == 3
        assert r.get("missing") == 0

    def test_resource_names(self):
        assert res(0, 0, {"g": 1}).resource_names() == ["cpu", "memory", "g"]


class TestVectorInterface:
    def test_roundtrip(self):
        r = res(1500, 2**30, {"nvidia.com/gpu": 2000})
        names = ["nvidia.com/gpu", "google.com/tpu"]
        vec = r.to_vector(names)
        assert vec == [1500, 2**30, 2000, 0.0]
        assert Resource.from_vector(vec, names) == r

    def test_epsilons_align(self):
        names = ["nvidia.com/gpu"]
        assert Resource.vector_epsilons(names) == [
            MIN_MILLI_CPU,
            MIN_MEMORY,
            MIN_MILLI_SCALAR,
        ]
