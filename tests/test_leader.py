"""Cluster-wide leader election through a store-backed lease (VERDICT r4
item 3): arbitration semantics in the store, the HTTP acquire/release
surface, and an HA pair of full scheduler servers failing over within
the lease window. Reference semantics:
cmd/kube-batch/app/server.go:115-139 (leaderelection.RunOrDie over a
ConfigMap resource lock, 15s/10s/5s)."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from kube_batch_tpu.cache import ClusterStore
from kube_batch_tpu.server import SchedulerServer, StoreLeaseElector
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_resource_list,
)


def wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# -- store-level arbitration (clock injected, no sleeps) --------------------


class TestLeaseArbitration:
    def test_fresh_acquire_and_renew(self):
        store = ClusterStore()
        l1 = store.try_acquire_lease("kb", "a", 15.0, now=100.0)
        assert l1.holder_identity == "a"
        assert l1.acquire_time == l1.renew_time == 100.0
        assert l1.lease_transitions == 0
        l2 = store.try_acquire_lease("kb", "a", 15.0, now=105.0)
        assert l2.holder_identity == "a"
        assert l2.acquire_time == 100.0  # original acquisition preserved
        assert l2.renew_time == 105.0
        assert l2.lease_transitions == 0

    def test_fresh_lease_is_not_stolen(self):
        store = ClusterStore()
        store.try_acquire_lease("kb", "a", 15.0, now=100.0)
        l = store.try_acquire_lease("kb", "b", 15.0, now=110.0)  # not expired
        assert l.holder_identity == "a"
        assert l.renew_time == 100.0  # contention attempt mutated nothing

    def test_expired_lease_is_taken_over(self):
        store = ClusterStore()
        store.try_acquire_lease("kb", "a", 15.0, now=100.0)
        l = store.try_acquire_lease("kb", "b", 15.0, now=100.0 + 15.01)
        assert l.holder_identity == "b"
        assert l.lease_transitions == 1
        assert l.acquire_time == 115.01

    def test_release_allows_instant_takeover(self):
        store = ClusterStore()
        store.try_acquire_lease("kb", "a", 15.0, now=100.0)
        store.release_lease("kb", "a")
        l = store.try_acquire_lease("kb", "b", 15.0, now=100.1)
        assert l.holder_identity == "b"
        assert l.lease_transitions == 1

    def test_release_by_non_holder_is_noop(self):
        store = ClusterStore()
        store.try_acquire_lease("kb", "a", 15.0, now=100.0)
        l = store.release_lease("kb", "b")
        assert l.holder_identity == "a"

    def test_empty_identity_rejected(self):
        store = ClusterStore()
        with pytest.raises(ValueError, match="identity"):
            store.try_acquire_lease("kb", "", 15.0, now=100.0)

    def test_pathological_durations_rejected(self):
        store = ClusterStore()
        for bad in (float("nan"), float("inf"), 0.0, -5.0, 1e9):
            with pytest.raises(ValueError, match="lease_duration"):
                store.try_acquire_lease("kb", "a", bad, now=100.0)

    def test_transient_renewal_blip_is_survived(self):
        """One failed renewal mid-window must not consume the whole
        deadline: the loop retries fast and a recovered arbiter keeps
        the leader alive."""
        import threading as _threading

        from kube_batch_tpu.server import StoreLeaseElector

        store = ClusterStore()
        el = StoreLeaseElector(
            store, "kb", "a", lease_duration=2.0,
            renew_deadline=1.0, retry_period=0.4,
        )
        assert el.acquire(blocking=False)
        real_try = el._try_acquire
        fails = {"n": 0}

        def flaky(timeout=5.0):
            if fails["n"] == 0:
                fails["n"] += 1
                raise OSError("transient arbiter blip")
            return real_try(timeout)

        el._try_acquire = flaky
        lost = _threading.Event()
        el.start_renewing(lost.set)
        assert not lost.wait(2.0), "single blip killed the leader"
        assert el.is_leader
        el._try_acquire = real_try
        el.release()

    def test_separate_lease_names_are_independent_scopes(self):
        store = ClusterStore()
        la = store.try_acquire_lease("scope-1", "a", 15.0, now=100.0)
        lb = store.try_acquire_lease("scope-2", "b", 15.0, now=100.0)
        assert la.holder_identity == "a" and lb.holder_identity == "b"


# -- HTTP surface + elector -------------------------------------------------


@pytest.fixture
def arbiter():
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    yield srv
    srv.stop()


def _url(server) -> str:
    return f"http://127.0.0.1:{server.listen_port}"


def test_http_acquire_release_roundtrip(arbiter):
    url = f"{_url(arbiter)}/apis/v1alpha1/leases/kb/acquire"
    req = urllib.request.Request(
        url,
        data=json.dumps({"identity": "x", "lease_duration": 15}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        body = json.loads(resp.read())
    assert body["acquired"] is True and body["holder"] == "x"
    # second contender is refused without mutating the lease
    req2 = urllib.request.Request(
        url,
        data=json.dumps({"identity": "y"}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req2, timeout=5) as resp:
        body2 = json.loads(resp.read())
    assert body2["acquired"] is False and body2["holder"] == "x"
    # lease appears on the list surface
    with urllib.request.urlopen(f"{_url(arbiter)}/apis/v1alpha1/leases", timeout=5) as r:
        items = json.loads(r.read())["items"]
    assert [l["holder"] for l in items] == ["x"]


def test_elector_pair_graceful_handoff(arbiter):
    a = StoreLeaseElector(
        _url(arbiter), "kb", "a", lease_duration=1.0,
        renew_deadline=0.7, retry_period=0.1,
    )
    b = StoreLeaseElector(
        _url(arbiter), "kb", "b", lease_duration=1.0,
        renew_deadline=0.7, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    assert not b.acquire(blocking=False)
    a.release()  # graceful: clears holder, standby takes over immediately
    assert b.acquire(blocking=False)
    b.release()


def test_elector_crash_failover_within_lease_window(arbiter):
    """Kill the leader WITHOUT release: the standby must take over once
    the lease expires — and not before."""
    lease_duration = 1.0
    a = StoreLeaseElector(
        _url(arbiter), "kb", "a", lease_duration=lease_duration,
        renew_deadline=0.7, retry_period=0.1,
    )
    b = StoreLeaseElector(
        _url(arbiter), "kb", "b", lease_duration=lease_duration,
        renew_deadline=0.7, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    # simulate a crash: renewals just stop; no graceful release
    t_death = time.monotonic()
    assert not b.acquire(blocking=False), "fresh lease must not be stolen"
    got = b.acquire(blocking=True)  # contends at retry_period cadence
    waited = time.monotonic() - t_death
    assert got
    # took over within the lease window (+ retry + slack), but only
    # after the lease actually expired
    assert waited >= lease_duration * 0.5
    assert waited < lease_duration + 1.0, f"failover took {waited:.2f}s"
    b.release()


def test_lost_leadership_fires_on_lost(arbiter):
    """A leader whose renewals stop succeeding (here: fenced out after
    expiry by a rival) learns it within renew_deadline and fires
    on_lost — the reference's OnStoppedLeading Fatalf hook."""
    a = StoreLeaseElector(
        _url(arbiter), "kb", "a", lease_duration=0.5,
        renew_deadline=0.4, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    lost = threading.Event()
    # Freeze a's renewals past expiry by taking the lease with a rival
    # after it expires, then let a's renewal thread discover the fence.
    a._stop.set()  # halt renewals before they start (simulated GC pause)
    time.sleep(0.6)  # lease expires
    b = StoreLeaseElector(
        _url(arbiter), "kb", "b", lease_duration=5.0,
        renew_deadline=4.0, retry_period=0.1,
    )
    assert b.acquire(blocking=False)
    a._stop.clear()  # pause ends; renewal loop starts and hits the fence
    a.start_renewing(lost.set)
    assert lost.wait(2.0), "fenced-out leader never learned it lost"
    assert not a.is_leader
    b.release()


def test_lease_name_scope_symmetric_across_transports(arbiter):
    """A name with '/' and ' ' must arbitrate the SAME scope whether the
    candidate talks HTTP (percent-encoded path) or holds the store
    in-process — asymmetric encoding would let both lead."""
    name = "team-a/kb one"
    via_http = StoreLeaseElector(
        _url(arbiter), name, "h", lease_duration=5.0,
        renew_deadline=4.0, retry_period=0.1,
    )
    in_proc = StoreLeaseElector(
        arbiter.store, name, "p", lease_duration=5.0,
        renew_deadline=4.0, retry_period=0.1,
    )
    assert via_http.acquire(blocking=False)
    assert not in_proc.acquire(blocking=False), "transports split the scope"
    via_http.release()
    assert in_proc.acquire(blocking=False)
    in_proc.release()


def test_renew_deadline_fires_before_lease_can_expire(arbiter):
    """Partitioned leader: the arbiter becomes unreachable right after
    acquisition. on_lost must fire within the renew deadline — strictly
    before the lease could expire under a standby — so two leaders can
    never overlap."""
    a = StoreLeaseElector(
        _url(arbiter), "kb", "a", lease_duration=2.0,
        renew_deadline=0.5, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    a.arbiter = "http://127.0.0.1:1"  # partition: nothing listens there
    lost = threading.Event()
    t0 = time.monotonic()
    a.start_renewing(lost.set)
    assert lost.wait(1.8), "partitioned leader never noticed"
    assert time.monotonic() - t0 < 2.0, "loss detected after possible expiry"
    assert not a.is_leader


# -- full HA pair: two scheduler servers, kill the leader -------------------


def test_ha_pair_failover_end_to_end(arbiter):
    """VERDICT r4 item 3 done-criterion: two scheduler servers contend on
    one arbiter; the leader schedules; kill it (no graceful release);
    the standby becomes leader within the lease window and ITS loop
    starts binding pods."""
    lease_duration = 1.0

    def make_server():
        srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=0.05)
        # a 1-pod workload in this server's own cluster store
        srv.store.create_node(
            build_node("n0", build_resource_list(cpu=4, memory="8Gi", pods=10))
        )
        srv.store.create_pod(
            build_pod(name="p0", req=build_resource_list(cpu=1, memory="1Gi"))
        )
        return srv

    def elector(identity):
        return StoreLeaseElector(
            _url(arbiter), "kb-ha", identity, lease_duration=lease_duration,
            renew_deadline=0.7, retry_period=0.1,
        )

    # leader: acquires, starts scheduling, renews
    el_a = elector("a")
    assert el_a.acquire(blocking=False)
    srv_a = make_server()
    srv_a.start()
    el_a.start_renewing(lambda: None)
    wait_until(
        lambda: all(p.node_name for p in srv_a.store.list("pods")),
        what="leader schedules",
    )

    # standby: blocked on the lease in a thread (run()'s blocking acquire)
    el_b = elector("b")
    srv_b = make_server()
    became_leader = threading.Event()

    def standby():
        if el_b.acquire(blocking=True):
            srv_b.start()  # OnStartedLeading
            became_leader.set()

    t = threading.Thread(target=standby, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not became_leader.is_set(), "standby must wait while leader renews"

    # kill the leader: loop + renewals stop dead, no release
    t_death = time.monotonic()
    el_a._stop.set()
    srv_a.stop()

    assert became_leader.wait(lease_duration + 1.5), "standby never took over"
    waited = time.monotonic() - t_death
    wait_until(
        lambda: all(p.node_name for p in srv_b.store.list("pods")),
        what="standby schedules after takeover",
    )
    assert waited < lease_duration + 1.0, f"failover took {waited:.2f}s"
    el_b.release()
    srv_b.stop()


def test_lost_leader_releases_lease_before_on_lost(arbiter):
    """ADVICE r5 (low): a renewal already in flight when the watchdog
    fires can land at the arbiter after this process decided it lost,
    extending a dead leader's lease by a full window. _lose now
    best-effort releases the lease BEFORE on_lost, so the standby takes
    over immediately instead of waiting out the (here: long) lease."""
    a = StoreLeaseElector(
        _url(arbiter), "kb-race", "a", lease_duration=30.0,
        renew_deadline=0.3, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    lost = threading.Event()

    def broken(timeout=5.0):
        raise OSError("injected renewal failure")

    a._try_acquire = broken  # renewals fail; the release POST still works
    a.start_renewing(lost.set)
    assert lost.wait(2.0), "leader never noticed the renewal failures"
    assert not a.is_leader
    # with a 30s lease, only an explicit release lets b in immediately
    b = StoreLeaseElector(
        _url(arbiter), "kb-race", "b", lease_duration=5.0,
        renew_deadline=4.0, retry_period=0.1,
    )
    assert b.acquire(blocking=False), "lease was not released on loss"
    b.release()
