"""Wire protocol v2 (ISSUE 17): the binary framing's loud failure
modes, the pump's decode-outside-lock contract, delta list+watch mirror
parity (including the 410 re-list heal), and the coalesced conditional
write path — bind-for-bind parity with per-gang dispatch under a
mutation detector, zero journal orphans after a SIGKILL mid-batch, and
the ``store.txn_batch`` chaos drill degrading loudly to per-gang v1
writes."""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.api.job_info import job_key
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis import wire
from kube_batch_tpu.cache import (
    EventHandler,
    LoopbackBackend,
    SchedulerCache,
)
from kube_batch_tpu.cache.store import KINDS, PODS, QUEUES
from kube_batch_tpu.faults.mutation_detector import MutationDetector
from kube_batch_tpu.federation import fsck
from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


@pytest.fixture
def make_arbiter():
    """Factory for store-arbiter servers (scheduling loop idled by a
    scheduler name no workload pod carries); stops them all at teardown
    so a failing test never leaks a listener thread."""
    servers: list[SchedulerServer] = []

    def _make(wire_protocol: int = 2) -> SchedulerServer:
        srv = SchedulerServer(
            scheduler_name="store-arbiter",
            listen_address="127.0.0.1:0",
            schedule_period=60.0,
            wire_protocol=wire_protocol,
        )
        srv.start()
        servers.append(srv)
        return srv

    yield _make
    for srv in servers:
        srv.stop()


def _base(arbiter) -> str:
    return f"http://127.0.0.1:{arbiter.listen_port}"


def seed_store(store, nodes=1, cpu=16, gangs=(), members=3):
    if store.get(QUEUES, "default") is None:  # the server pre-seeds one
        store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(
                f"n{i}", build_resource_list(cpu=cpu, memory=f"{cpu}Gi", pods=64)
            )
        )
    for g in gangs:
        store.create_pod_group(build_pod_group(g, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{g}-p{m}", group_name=g,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def bind_gangs(cache, mapping: dict):
    """Dispatch every pending task of each gang in ONE bind_many call —
    the shape the coalescer batches: all gangs of one cycle, one txn
    round trip."""
    pairs = []
    with cache._mutex:
        for gang, node in mapping.items():
            job = cache.jobs.get(job_key("default", gang))
            pending = (
                list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
                if job is not None
                else []
            )
            assert pending, f"gang {gang} has no pending tasks in this cache"
            pairs.extend((t, node) for t in pending)
    cache.bind_many(pairs)


def count_bind_events(store):
    counts: dict[str, int] = {}
    lock = threading.Lock()

    def on_update(old, new):
        if not old.node_name and new.node_name:
            with lock:
                key = f"{new.namespace}/{new.name}"
                counts[key] = counts.get(key, 0) + 1

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    return counts


def mirror_snap(backend) -> dict:
    """Canonical bytes of a backend mirror: kind -> key -> sorted wire
    JSON. Two mirrors fed through different transports (full-object v1
    vs delta v2, json vs binary) must be byte-identical here."""
    with backend._lock:
        return {
            kind: {
                key: json.dumps(wire.encode_kind(kind, obj), sort_keys=True)
                for key, obj in backend._mirror[kind].items()
            }
            for kind in backend.kinds
        }


# -- binary framing ----------------------------------------------------------


def test_binary_codec_self_check_and_size_win():
    s = wire.self_check(seed=1, cases=20)
    assert s["ok"], s["errors"]
    assert s["failures"] == 0
    # the headline property of the binary framing: strictly fewer bytes
    # than the same objects through the JSON codec
    assert s["binary_bytes"] < s["json_bytes"]


def test_binary_frame_rejects_garbage_loudly():
    # JSON bytes handed to the binary decoder: the codec-mismatch case —
    # the error must point at the triage ladder, not be a struct error
    with pytest.raises(ValueError, match="codec mismatch"):
        wire.loads_binary(b'{"storeVersion": 3}')
    blob = wire.dumps_binary({"a": 1, "b": [1, 2, 3]})
    assert wire.loads_binary(blob) == {"a": 1, "b": [1, 2, 3]}
    with pytest.raises(ValueError, match="length mismatch"):
        wire.loads_binary(blob[:-2])
    with pytest.raises(ValueError, match="codec mismatch"):
        wire.loads_binary(b"XXXX" + blob[4:])


def test_bad_codec_pref_falls_back_to_json():
    # an unknown KBT_WIRE_CODEC must degrade to json (loudly, in the
    # log), never crash the backend at construction
    b = LoopbackBackend("http://127.0.0.1:9", codec="gzip")
    assert b._codec_pref == "json"


def test_binary_body_to_v1_server_is_rejected_loudly(make_arbiter):
    # a v2 client that skipped renegotiation after a rolling downgrade
    # would POST binary at a v1 server: the reply must be a loud 400
    # JSON error naming the fix, not a silent mis-parse
    srv = make_arbiter(wire_protocol=1)
    req = urllib.request.Request(
        f"{_base(srv)}/backend/v1/bind",
        data=wire.dumps_binary({"bindings": []}),
        headers={"Content-Type": wire.BINARY_CONTENT_TYPE},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 400
    payload = json.loads(ei.value.read())
    assert "binary request body on a v1 server" in payload["error"]
    assert "re-negotiate" in payload["error"]


# -- pump lock discipline (satellite: decode outside _lock) ------------------


def test_pump_decodes_wire_payloads_outside_mirror_lock(
    make_arbiter, monkeypatch
):
    """Regression for the pump stall: decoding a fat payload under
    ``_lock`` blocks every concurrent mirror read for the duration.
    Every ``decode_kind`` call (initial list, watch events, re-list
    heal) must run with the mirror lock acquirable from another thread
    — probed cross-thread because ``_lock`` is an RLock and a
    same-thread acquire would always succeed."""
    srv = make_arbiter()
    seed_store(srv.store, gangs=("g0",), members=2)
    backend = LoopbackBackend(_base(srv), kinds=(PODS,))

    real_decode = wire.decode_kind
    probes: list[bool] = []

    def spying_decode(kind, data):
        acquired = []

        def probe():
            ok = backend._lock.acquire(timeout=2.0)
            if ok:
                backend._lock.release()
            acquired.append(ok)

        t = threading.Thread(target=probe)
        t.start()
        t.join(timeout=5.0)
        probes.extend(acquired or [False])
        return real_decode(kind, data)

    monkeypatch.setattr(wire, "decode_kind", spying_decode)
    backend.add_event_handler(PODS, EventHandler())  # initial list decodes
    srv.store.create_pod(
        build_pod(name="late", req=build_resource_list(cpu=1))
    )
    assert backend.pump() >= 1  # watch event decodes
    faults.registry.arm("watch.drop", count=1)
    srv.store.create_pod(
        build_pod(name="later", req=build_resource_list(cpu=1))
    )
    assert backend.pump() >= 1  # 410 -> re-list heal decodes
    assert probes, "decode_kind never ran — the drill lost its subject"
    assert all(probes), "mirror lock was held during a wire decode"
    assert backend.get_pod("default", "later") is not None


# -- delta list+watch --------------------------------------------------------


def test_delta_mirror_matches_full_object_mirror_through_schedule(
    make_arbiter,
):
    """Acceptance drill: a delta-watch (v2, binary) mirror must be
    byte-identical to a full-object (v1, json) mirror after the same
    event schedule — adds, gang binds, deletes, and a forced 410 heal
    mid-run."""
    srv = make_arbiter()
    seed_store(srv.store, nodes=2, gangs=("g0", "g1"), members=2)
    b_full = LoopbackBackend(_base(srv), protocol=1)
    b_delta = LoopbackBackend(_base(srv))
    for b in (b_full, b_delta):
        for kind in KINDS:
            b.add_event_handler(kind, EventHandler())
    assert b_delta._protocol == 2 and "delta" in b_delta._features
    assert b_full._protocol == 1
    assert mirror_snap(b_delta) == mirror_snap(b_full)

    # adds + a gang bind (field-level MODIFIED deltas on pods/nodes)
    srv.store.create_pod(
        build_pod(name="late", req=build_resource_list(cpu=1))
    )
    v = srv.store.version
    srv.store.conditional_bind_many(
        [("default", "g0-p0", "n0"), ("default", "g0-p1", "n0")], v
    )
    srv.store.delete_pod("default", "late")
    assert b_delta.pump() >= 1
    assert b_full.pump() >= 1
    snap = mirror_snap(b_delta)
    assert snap == mirror_snap(b_full)
    assert json.loads(snap[PODS]["default/g0-p0"])["node_name"] == "n0"
    assert "default/late" not in snap[PODS]

    # 410 heal: the delta watcher's cursor is declared gone mid-run;
    # it must re-list and land on the exact same bytes as the v1 twin
    faults.registry.arm("watch.drop", count=1)
    v = srv.store.version
    srv.store.conditional_bind_many(
        [("default", "g1-p0", "n1"), ("default", "g1-p1", "n1")], v
    )
    assert b_delta.pump() >= 1  # consumes the fault: gone -> re-list
    assert b_full.pump() >= 1
    snap = mirror_snap(b_delta)
    assert snap == mirror_snap(b_full)
    assert json.loads(snap[PODS]["default/g1-p1"])["node_name"] == "n1"
    # and both match server truth
    for g, n in (("g0", "n0"), ("g1", "n1")):
        for m in range(2):
            assert b_delta.get_pod("default", f"{g}-p{m}").node_name == n


# -- coalesced conditional writes --------------------------------------------


def _cache_over(srv, **kwargs) -> SchedulerCache:
    cache = SchedulerCache(
        LoopbackBackend(_base(srv)), conditional_binds=True, **kwargs
    )
    cache.snapshot()  # stamp _snapshot_version for conditional dispatch
    return cache


GANG_NODES = {"ga": "n0", "gb": "n1", "gc": "n2"}


def test_coalesced_txn_parity_with_per_gang_dispatch(
    make_arbiter, monkeypatch
):
    """Acceptance drill: the same three-gang cycle through the coalesced
    /backend/v1/txn path and through per-gang conditional writes must
    land bind-for-bind identical placements — exactly once, mutation
    detector armed, fsck clean — with exactly one batch observed."""
    srv_txn = make_arbiter()
    srv_gang = make_arbiter()
    for srv in (srv_txn, srv_gang):
        seed_store(srv.store, nodes=3, gangs=tuple(GANG_NODES), members=2)
    counts_txn = count_bind_events(srv_txn.store)
    counts_gang = count_bind_events(srv_gang.store)
    det_txn = MutationDetector(srv_txn.store)
    det_gang = MutationDetector(srv_gang.store)
    det_txn.snapshot()
    det_gang.snapshot()

    cache_txn = _cache_over(srv_txn)  # KBT_TXN_COALESCE default: on
    assert cache_txn._txn_coalesce and cache_txn.store.supports_txn()
    monkeypatch.setenv("KBT_TXN_COALESCE", "0")
    cache_gang = _cache_over(srv_gang)
    assert not cache_gang._txn_coalesce

    txn0 = metrics.store_backend_txn_batch.snapshot()
    bind_gangs(cache_txn, GANG_NODES)
    bind_gangs(cache_gang, GANG_NODES)
    txn1 = metrics.store_backend_txn_batch.snapshot()

    # one batch carrying all three gangs, and only from the coalescing
    # cache — the per-gang twin never touched /backend/v1/txn
    assert txn1["count"] == txn0["count"] + 1
    assert txn1["sum"] == txn0["sum"] + len(GANG_NODES)

    for g, n in GANG_NODES.items():
        for m in range(2):
            p_txn = srv_txn.store.get_pod("default", f"{g}-p{m}")
            p_gang = srv_gang.store.get_pod("default", f"{g}-p{m}")
            assert p_txn.node_name == n == p_gang.node_name
    expected = sorted(f"default/{g}-p{m}" for g in GANG_NODES for m in range(2))
    for counts in (counts_txn, counts_gang):
        assert sorted(counts) == expected
        assert all(c == 1 for c in counts.values()), f"duplicates: {counts}"
    assert det_txn.violations() == [] and det_gang.violations() == []
    assert fsck(srv_txn.store) == [] and fsck(srv_gang.store) == []


class _Killed(BaseException):
    """SIGKILL stand-in (BaseException: no retry ladder survives it)."""


class _DyingBackend(LoopbackBackend):
    """Dies exactly at the coalesced submit — after the journal holds
    every gang's intents, before any write reaches the store."""

    def submit_txn(self, txns):
        raise _Killed()


def test_txn_sigkill_mid_batch_leaves_no_journal_orphans(
    make_arbiter, tmp_path
):
    """Acceptance drill: leader killed mid-coalesced-batch — nothing
    landed, the journal holds the whole cycle as orphans, and standby
    reconciliation re-drives every gang exactly once (fsck clean,
    mutation detector clean, zero orphans on re-replay)."""
    srv = make_arbiter()
    seed_store(srv.store, nodes=2, gangs=("ga", "gb"), members=2)
    counts = count_bind_events(srv.store)
    journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    cache = SchedulerCache(
        _DyingBackend(_base(srv)), conditional_binds=True, journal=journal
    )
    cache.snapshot()
    assert cache.store.supports_txn()
    with pytest.raises(_Killed):
        bind_gangs(cache, {"ga": "n0", "gb": "n1"})

    # died before anything reached the store: all four intents orphaned
    pods = [f"default/{g}-p{m}" for g in ("ga", "gb") for m in range(2)]
    for key in pods:
        ns, name = key.split("/")
        assert not srv.store.get_pod(ns, name).node_name
    orphans = WriteIntentJournal.replay(journal.path).orphans
    assert sorted((i.op, i.pod) for i in orphans) == sorted(
        ("bind", p) for p in pods
    )

    # standby takeover: reconcile the WAL against store truth
    standby = WriteIntentJournal(journal.path)
    det = MutationDetector(srv.store)
    det.snapshot()
    report = reconcile_journal(standby, srv.store)
    assert report.redispatched == len(pods) and report.rolled_back == 0
    for g, n in (("ga", "n0"), ("gb", "n1")):
        for m in range(2):
            assert srv.store.get_pod("default", f"{g}-p{m}").node_name == n
    assert sorted(counts) == sorted(pods)
    assert all(c == 1 for c in counts.values()), f"duplicates: {counts}"
    assert det.violations() == []
    assert fsck(srv.store) == []
    assert WriteIntentJournal.replay(journal.path).orphans == []
    journal.close()
    standby.close()


@pytest.mark.chaos
def test_chaos_txn_batch_fault_degrades_loudly_to_per_gang(
    make_arbiter, monkeypatch
):
    """store.txn_batch armed mid-batch: the coalesced path must degrade
    LOUDLY to per-gang conditional writes — every pod still lands
    exactly once, no batch is observed, and the degradation is named in
    the error log."""
    srv = make_arbiter()
    seed_store(srv.store, nodes=2, gangs=("ga", "gb"), members=2)
    counts = count_bind_events(srv.store)
    cache = _cache_over(srv)
    assert cache.store.supports_txn()

    errors: list[str] = []
    real_errorf = log.errorf

    def spying_errorf(fmt, *args):
        errors.append(fmt % args if args else fmt)
        real_errorf(fmt, *args)

    monkeypatch.setattr(log, "errorf", spying_errorf)
    txn0 = metrics.store_backend_txn_batch.snapshot()
    faults.registry.arm("store.txn_batch", count=1)
    bind_gangs(cache, {"ga": "n0", "gb": "n1"})

    assert any(
        "degrading 2 gang(s) to per-gang conditional writes" in e
        for e in errors
    ), errors
    # no batch landed — the cycle went out as per-gang v1 writes
    assert metrics.store_backend_txn_batch.snapshot()["count"] == txn0["count"]
    for g, n in (("ga", "n0"), ("gb", "n1")):
        for m in range(2):
            assert srv.store.get_pod("default", f"{g}-p{m}").node_name == n
    expected = sorted(f"default/{g}-p{m}" for g in ("ga", "gb") for m in range(2))
    assert sorted(counts) == expected
    assert all(c == 1 for c in counts.values()), f"duplicates: {counts}"
    assert fsck(srv.store) == []
