"""tensorscore ≡ nodeorder: the vectorized scoring plugin must place
pods identically to the serial scoring plugin under every action path
(SURVEY.md section 2.7d — vectorized scoring toggleable via conf)."""

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu import plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import multi_tenant_ml, synthetic
from kube_batch_tpu.testing import FakeCache

from test_xla_allocate import gen_cluster


def tiers_with(score_plugin: str, action: str = "allocate"):
    return parse_scheduler_conf(
        f"""
actions: "{action}"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: {score_plugin}
"""
    ).tiers


def run(action, cluster, score_plugin):
    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers_with(score_plugin, action))
    get_action(action).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return state, dict(cache.binder.binds), list(cache.evictor.evicts)


def assert_same_outcome(make_cluster, action="allocate"):
    n_state, n_binds, n_ev = run(action, make_cluster(), "nodeorder")
    t_state, t_binds, t_ev = run(action, make_cluster(), "tensorscore")
    assert t_binds == n_binds
    assert t_state == n_state
    assert t_ev == n_ev


def test_allocate_synthetic():
    assert_same_outcome(lambda: synthetic(300, 30))


def test_allocate_scalar_resources():
    assert_same_outcome(lambda: multi_tenant_ml(n_jobs=10, n_nodes=10, n_queues=4))


def test_property_sweep():
    for seed in range(16):
        n = run("allocate", gen_cluster(seed), "nodeorder")
        t = run("allocate", gen_cluster(seed), "tensorscore")
        assert t == n, f"seed {seed} diverged"


def test_preempt_with_tensorscore():
    from test_xla_preempt import gen_contended_cluster

    for seed in range(8):
        n = run("preempt", gen_contended_cluster(seed), "nodeorder")
        t = run("preempt", gen_contended_cluster(seed), "tensorscore")
        assert t == n, f"seed {seed} diverged"


def test_xla_allocate_accepts_tensorscore_conf():
    """The kernel envelope treats tensorscore as nodeorder (same scores):
    xla_allocate under a tensorscore conf == serial allocate under it."""
    s = run("allocate", synthetic(200, 20), "tensorscore")
    x = run("xla_allocate", synthetic(200, 20), "tensorscore")
    assert x == s
    assert len(s[1]) == 200
