"""tensorscore ≡ nodeorder: the vectorized scoring plugin must place
pods identically to the serial scoring plugin under every action path
(SURVEY.md section 2.7d — vectorized scoring toggleable via conf)."""

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu import plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import multi_tenant_ml, synthetic
from kube_batch_tpu.testing import FakeCache

from test_xla_allocate import gen_cluster


def tiers_with(score_plugin: str, action: str = "allocate"):
    return parse_scheduler_conf(
        f"""
actions: "{action}"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: {score_plugin}
"""
    ).tiers


def run(action, cluster, score_plugin):
    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers_with(score_plugin, action))
    get_action(action).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return state, dict(cache.binder.binds), list(cache.evictor.evicts)


def assert_same_outcome(make_cluster, action="allocate"):
    n_state, n_binds, n_ev = run(action, make_cluster(), "nodeorder")
    t_state, t_binds, t_ev = run(action, make_cluster(), "tensorscore")
    assert t_binds == n_binds
    assert t_state == n_state
    assert t_ev == n_ev


def test_allocate_synthetic():
    assert_same_outcome(lambda: synthetic(300, 30))


def test_allocate_scalar_resources():
    assert_same_outcome(lambda: multi_tenant_ml(n_jobs=10, n_nodes=10, n_queues=4))


def test_property_sweep():
    for seed in range(16):
        n = run("allocate", gen_cluster(seed), "nodeorder")
        t = run("allocate", gen_cluster(seed), "tensorscore")
        assert t == n, f"seed {seed} diverged"


def test_preempt_with_tensorscore():
    from test_xla_preempt import gen_contended_cluster

    for seed in range(8):
        n = run("preempt", gen_contended_cluster(seed), "nodeorder")
        t = run("preempt", gen_contended_cluster(seed), "tensorscore")
        assert t == n, f"seed {seed} diverged"


def test_xla_allocate_tensorscore_multi_pause_pod_affinity():
    """Round-3 advisor finding: xla_allocate's bulk replay mutates
    node.used without bumping ssn.state_seq, so with 2+ host-stepped
    pod-affinity pauses tensorscore scored the later pause with stale
    Used vectors. Two required-affinity pods separated by filler
    assignments must land exactly where the serial scorer puts them."""
    from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    def mk():
        pods, groups = [], []
        # anchors make n0/n1 eligible for the required-affinity pods
        for i in (0, 1):
            pods.append(
                build_pod(
                    name=f"anchor{i}",
                    node_name=f"n{i}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="128Mi"),
                    labels={"app": "db"},
                )
            )

        def gang(name, pod, ts):
            pod.metadata.creation_timestamp = ts
            pg = build_pod_group(name, min_member=1)
            pg.metadata.creation_timestamp = ts
            pods.append(pod)
            groups.append(pg)

        aff1 = build_pod(
            name="aff1", group_name="g-aff1", req=build_resource_list(cpu=1, memory="256Mi")
        )
        aff1.affinity = Affinity(
            pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
        )
        gang("g-aff1", aff1, 0.0)
        # fillers shift the least-requested balance between n0 and n1
        # after aff1's pause — a stale Used memo misses their effect
        for i in range(4):
            gang(
                f"g-fill{i}",
                build_pod(
                    name=f"fill{i}",
                    group_name=f"g-fill{i}",
                    req=build_resource_list(cpu=2, memory="2Gi"),
                ),
                1.0 + i,
            )
        aff2 = build_pod(
            name="aff2", group_name="g-aff2", req=build_resource_list(cpu=1, memory="256Mi")
        )
        aff2.affinity = Affinity(
            pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
        )
        gang("g-aff2", aff2, 10.0)
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
            for i in range(3)
        ]
        return build_cluster(pods, nodes, groups, [build_queue("default")])

    serial = run("allocate", mk(), "tensorscore")
    vector = run("xla_allocate", mk(), "tensorscore")
    assert vector == serial
    oracle = run("allocate", mk(), "nodeorder")
    assert serial == oracle


def test_xla_allocate_accepts_tensorscore_conf():
    """The kernel envelope treats tensorscore as nodeorder (same scores):
    xla_allocate under a tensorscore conf == serial allocate under it."""
    s = run("allocate", synthetic(200, 20), "tensorscore")
    x = run("xla_allocate", synthetic(200, 20), "tensorscore")
    assert x == s
    assert len(s[1]) == 200
