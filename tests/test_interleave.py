"""Tier-1 tests for the interleaving model checker
(kube_batch_tpu.analysis.interleave).

Three layers: the schedule enumerator as a pure unit (canonical-form
pruning), the explorer end to end (the four default scenarios explore
clean, deterministically), and the counterexample loop (the
intentionally broken ``broken_drain`` fixture fails at exactly one
trace id, which replays to the same violation — the seeded-replay
contract the runbook's triage loop depends on)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from kube_batch_tpu.analysis import interleave
from kube_batch_tpu.analysis.interleave import (
    FIXTURES,
    SCENARIOS,
    Step,
    _schedules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- enumerator unit ----------------------------------------------------------


def _step(name, fp):
    return Step(name, lambda: None, frozenset(fp))


def test_enumeration_keeps_one_order_per_commuting_pair():
    # disjoint footprints: the two orders are the same trace -> 1 form
    orders, pruned = _schedules([[_step("a", {"x"})], [_step("b", {"y"})]])
    assert orders == [(0, 1)]
    assert pruned == 1


def test_enumeration_keeps_both_orders_of_a_conflicting_pair():
    orders, pruned = _schedules([[_step("a", {"x"})], [_step("b", {"x"})]])
    assert orders == [(0, 1), (1, 0)]
    assert pruned == 0


def test_enumeration_counts_interleavings_of_conflicting_threads():
    # 2+2 steps, everything conflicts: C(4,2) = 6 distinct schedules
    t0 = [_step("a0", {"x"}), _step("a1", {"x"})]
    t1 = [_step("b0", {"x"}), _step("b1", {"x"})]
    orders, _ = _schedules([t0, t1])
    assert len(orders) == 6
    assert len(set(orders)) == 6


# -- the four default scenarios ----------------------------------------------


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_default_scenarios_explore_clean(name):
    report = interleave.explore(name)
    assert report.schedules >= 3
    assert report.counterexamples == [], [
        r.violations for r in report.counterexamples
    ]


def test_explorer_is_deterministic_across_runs():
    a = interleave.explore("broken_drain")
    b = interleave.explore("broken_drain")
    assert [r.trace for r in a.results] == [r.trace for r in b.results]
    assert [r.violations for r in a.results] == [r.violations for r in b.results]


# -- the counterexample loop --------------------------------------------------


def test_broken_fixture_fails_at_exactly_one_trace():
    report = interleave.explore("broken_drain")
    assert report.schedules == 3
    assert [r.trace for r in report.counterexamples] == ["broken_drain:011"]
    (bad,) = report.counterexamples
    assert any("lost" in v for v in bad.violations)
    findings = report.findings()
    assert findings and all(f.code == "KBT-I001" for f in findings)
    assert "--replay broken_drain:011" in findings[0].message


def test_counterexample_replays_by_trace_id():
    bad = interleave.replay("broken_drain:011")
    assert any("lost" in v for v in bad.violations)
    # the neighboring schedule is clean: the race, not the fixture world,
    # is what the trace id pins
    ok = interleave.replay("broken_drain:101")
    assert ok.violations == []


def test_undeclared_lock_acquisition_is_a_model_error(tmp_path):
    class Mini(interleave.Scenario):
        name = "mini"
        parity = False

        def build(self):
            self._wire(nodes=1)
            self.threads = [
                [Step("peek_store", lambda: self.store.list("pods"), frozenset())]
            ]

        def invariants(self):
            return []

    result = interleave._run_schedule(Mini, str(tmp_path), (0,), "mini:0")
    assert any("footprint under-declared" in v for v in result.violations)


def test_fixture_is_excluded_from_the_default_set():
    assert "broken_drain" in FIXTURES
    assert "broken_drain" not in SCENARIOS


# -- CLI ----------------------------------------------------------------------


def test_cli_json_reports_counterexample_and_fails():
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis.interleave",
         "--scenario", "broken_drain", "--json"],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 1, res.stdout + res.stderr
    payload = json.loads(res.stdout)
    (scenario,) = payload["scenarios"]
    assert scenario["name"] == "broken_drain"
    assert [c["trace"] for c in scenario["counterexamples"]] == ["broken_drain:011"]
    assert any("broken_drain:011" in f for f in payload["findings"])
