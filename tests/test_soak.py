"""Committed soak/churn test (VERDICT r3 item 6, reference
test/kubemark methodology at CI-tolerable scale): thousands of pods
churned through the live server loop over hundreds of cycles, asserting
no job/task leaks in the cache or store and bounded process RSS."""

from __future__ import annotations

import resource
import time

import pytest

from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_resource_list,
)


def wait_until(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


@pytest.mark.slow
def test_soak_churn_no_leaks():
    """5k pods over 100 generations (hundreds of scheduler cycles at a
    20ms period): every generation creates gangs, waits for binds,
    deletes the pods and groups, and the cache must drain completely —
    jobs GC'd through deletedJobs, no task residue on nodes, store
    empty — with peak RSS growth bounded."""
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=0.02)
    srv.start()
    store = srv.store
    cache = srv.cache
    n_nodes, gangs_per_gen, gang_size, generations = 20, 5, 10, 100
    try:
        for i in range(n_nodes):
            store.create_node(
                build_node(f"n{i:02d}", build_resource_list(cpu=16, memory="32Gi", pods=110))
            )

        warmup_rss = None
        for gen in range(generations):
            names = []
            for g in range(gangs_per_gen):
                pg_name = f"gen{gen}-g{g}"
                store.create_pod_group(build_pod_group(pg_name, min_member=gang_size))
                for t in range(gang_size):
                    store.create_pod(
                        build_pod(
                            name=f"{pg_name}-t{t}",
                            group_name=pg_name,
                            req=build_resource_list(cpu=1, memory="1Gi"),
                        )
                    )
                names.append(pg_name)

            expected = gangs_per_gen * gang_size
            wait_until(
                lambda: sum(
                    1 for p in store.list("pods") if p.node_name and p.metadata.name.startswith(f"gen{gen}-")
                )
                == expected,
                what=f"generation {gen} fully bound",
            )

            # completion + teardown: delete pods and their groups
            for pg_name in names:
                for t in range(gang_size):
                    store.delete_pod("default", f"{pg_name}-t{t}")
                store.delete_pod_group("default", pg_name)

            if gen == 4:
                warmup_rss = rss_mb()

        # -- leak assertions -------------------------------------------
        assert store.list("pods") == []
        assert store.list("podgroups") == []
        wait_until(
            lambda: len(cache.jobs) == 0,
            what=f"cache job GC (left: {list(cache.jobs)[:5]})",
        )
        for node in cache.nodes.values():
            assert node.tasks == {}, f"task residue on {node.name}"
            assert node.used.milli_cpu == 0, f"used residue on {node.name}"
        # errTasks should hold nothing once everything bound cleanly
        assert len(cache._err_tasks) == 0

        growth = rss_mb() - warmup_rss
        assert growth < 200, f"peak RSS grew {growth:.0f}MB over the churn"
    finally:
        srv.stop()
