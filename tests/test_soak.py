"""Committed soak/churn tests (VERDICT r3 item 6, reference
test/kubemark methodology at CI-tolerable scale): thousands of pods
churned through the live server loop over hundreds of cycles, asserting
no job/task leaks in the cache or store and bounded process RSS — once
through the default serial pipeline, once through the full TPU conf
(xla actions + tensorscore), sharing one churn driver so the two stay
assertion-identical."""

from __future__ import annotations

import pathlib
import resource
import time

import pytest

from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_resource_list,
)

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def wait_until(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def churn(
    scheduler_conf,
    generations,
    schedule,
    n_nodes,
    warmup_gen,
    rss_budget_mb,
    bind_timeout,
    prefix,
):
    """One soak run: per generation create the scheduled gangs, wait for
    every pod to bind, tear everything down; afterwards the store and
    cache must drain completely and peak RSS growth past `warmup_gen`
    stays under budget. `schedule(gen) -> (gangs, gang_size)`."""
    srv = SchedulerServer(
        listen_address="127.0.0.1:0",
        schedule_period=0.02,
        scheduler_conf=scheduler_conf,
    )
    srv.start()
    store = srv.store
    cache = srv.cache
    try:
        for i in range(n_nodes):
            store.create_node(
                build_node(
                    f"n{i:02d}", build_resource_list(cpu=16, memory="32Gi", pods=110)
                )
            )
        warmup_rss = None
        for gen in range(generations):
            gangs, size = schedule(gen)
            names = []
            for g in range(gangs):
                pg_name = f"{prefix}{gen}-g{g}"
                store.create_pod_group(build_pod_group(pg_name, min_member=size))
                for t in range(size):
                    store.create_pod(
                        build_pod(
                            name=f"{pg_name}-t{t}",
                            group_name=pg_name,
                            req=build_resource_list(cpu=1, memory="1Gi"),
                        )
                    )
                names.append(pg_name)
            expected = gangs * size
            wait_until(
                lambda: sum(
                    1
                    for p in store.list("pods")
                    if p.node_name and p.metadata.name.startswith(f"{prefix}{gen}-")
                )
                == expected,
                timeout=bind_timeout(gen),
                what=f"generation {gen} fully bound",
            )
            for pg_name in names:
                for t in range(size):
                    store.delete_pod("default", f"{pg_name}-t{t}")
                store.delete_pod_group("default", pg_name)
            if gen == warmup_gen:
                warmup_rss = rss_mb()

        # -- leak assertions (identical for every pipeline) ---------------
        assert store.list("pods") == []
        assert store.list("podgroups") == []
        wait_until(
            lambda: len(cache.jobs) == 0,
            what=f"cache job GC (left: {list(cache.jobs)[:5]})",
        )
        for node in cache.nodes.values():
            assert node.tasks == {}, f"task residue on {node.name}"
            assert node.used.milli_cpu == 0, f"used residue on {node.name}"
        # errTasks should hold nothing once everything bound cleanly
        assert len(cache._err_tasks) == 0
        growth = rss_mb() - warmup_rss
        assert growth < rss_budget_mb, (
            f"peak RSS grew {growth:.0f}MB over the churn"
        )
    finally:
        srv.stop()


@pytest.mark.slow
def test_soak_churn_no_leaks():
    """5k pods over 100 generations (hundreds of scheduler cycles at a
    20ms period) through the default serial pipeline."""
    churn(
        scheduler_conf=None,
        generations=100,
        schedule=lambda gen: (5, 10),
        n_nodes=20,
        warmup_gen=4,
        rss_budget_mb=200,
        bind_timeout=lambda gen: 30,
        prefix="gen",
    )


@pytest.mark.slow
def test_soak_churn_tpu_pipeline():
    """The same churn through the full TPU conf (xla_reclaim,
    xla_allocate, xla_backfill, xla_preempt + tensorscore): every
    generation's gangs bind via encode → device solve → bulk replay —
    catching leaks in the encoder caches, solver state, or the native
    bulk-replay surgery, plus compile-cache stability across padding
    buckets. The (gangs, size) schedule has period 6, so generations
    0-5 each introduce a fresh (task, job) bucket combo and get the
    full jit-compile timeout; RSS warmup is sampled only after every
    bucket shape has been seen."""
    churn(
        scheduler_conf=str(EXAMPLES / "scheduler-conf-tpu.yaml"),
        generations=30,
        schedule=lambda gen: (3 + (gen % 3) * 2, 6 + (gen % 2) * 6),
        n_nodes=16,
        warmup_gen=5,
        rss_budget_mb=300,
        bind_timeout=lambda gen: 180 if gen < 6 else 30,
        prefix="tgen",
    )
