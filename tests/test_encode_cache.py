"""Incremental cross-cycle encoder (ISSUE 5): parity, invalidation,
arena, and chaos coverage for ops/encode_cache.py.

The contract under test: with ``KBT_ENCODE_CACHE`` on (the default), a
warm encode — and a churned re-encode — is **byte-identical** to a cold
encode of the same world, and every scheduling path (serial action, XLA
twin, the mesh rungs at {1,2,4,8} devices) places bind-for-bind
identically to the cache-off path.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu import faults, metrics
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import multi_queue, synthetic
from kube_batch_tpu.ops import encode_cache
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

CONF = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Every test starts cold and leaves no armed faults behind."""
    encode_cache.get().invalidate_all("test")
    faults.registry.reset()
    yield
    encode_cache.get().invalidate_all("test")
    faults.registry.reset()
    os.environ.pop("KBT_ENCODE_CACHE", None)


def _tiers():
    return parse_scheduler_conf(CONF).tiers


def _encode(ssn, dtype=np.float64):
    return encode_session(
        ssn.jobs, ssn.nodes, ssn.queues, dtype=dtype,
        drf=ssn.plugins.get("drf"), proportion=ssn.plugins.get("proportion"),
        session=ssn,
    )


def _assert_arrays_equal(a, b, what=""):
    assert set(a.arrays) == set(b.arrays)
    for k in a.arrays:
        x, y = np.asarray(a.arrays[k]), np.asarray(b.arrays[k])
        assert x.shape == y.shape and x.dtype == y.dtype, f"{what} arrays[{k}]"
        assert np.array_equal(x, y), f"{what} arrays[{k}] diverges"


# -- encode-level parity -----------------------------------------------------


def test_warm_encode_byte_identical_to_cold():
    ssn = open_session(FakeCache(multi_queue(400, 64)), _tiers())
    cold = _encode(ssn)
    warm = _encode(ssn)
    _assert_arrays_equal(cold, warm, "warm")
    assert encode_cache.get().warm_fraction > 0.5
    assert [t.uid for t in warm.tasks] == [t.uid for t in cold.tasks]
    close_session(ssn)


def test_churned_encode_identical_to_fresh_cold():
    """Node churn (label flip via set_node — the watch-event shape) must
    invalidate exactly the churned rows: the re-encode equals a fully
    cold encode of the churned world."""
    ssn = open_session(FakeCache(multi_queue(400, 64)), _tiers())
    _encode(ssn)
    for name in sorted(ssn.nodes)[:3]:
        ssn.nodes[name].set_node(
            build_node(
                name,
                build_resource_list(cpu=8, memory="16Gi", pods=110),
                labels={"churn/zone": "z1"},
            )
        )
    churn = _encode(ssn)
    encode_cache.get().invalidate_all("test")
    cold = _encode(ssn)
    _assert_arrays_equal(cold, churn, "churn")
    close_session(ssn)


def test_session_mutation_invalidates_task_block():
    """state_seq is the task block's freshness key: after the session
    mutates (an allocate), the re-encode must see the shrunken pending
    set, not the cached rows."""
    ssn = open_session(FakeCache(multi_queue(120, 16)), _tiers())
    enc1 = _encode(ssn)
    task = enc1.tasks[0]
    node = next(iter(ssn.nodes.values()))
    ssn.allocate(task, node.name)
    enc2 = _encode(ssn)
    assert enc2.n_tasks == enc1.n_tasks - 1
    encode_cache.get().invalidate_all("test")
    cold = _encode(ssn)
    _assert_arrays_equal(cold, enc2, "post-mutation")
    close_session(ssn)


def test_selector_affinity_world_parity():
    """Signature-heavy world (selectors + labeled nodes): the pair memo
    must reproduce the compat/affinity products exactly."""
    nodes = [
        build_node(
            f"n{i:03d}",
            build_resource_list(cpu=4, memory="8Gi", pods=20),
            labels={"disk": "ssd" if i % 2 else "hdd", "zone": f"z{i % 3}"},
        )
        for i in range(24)
    ]
    pods, pgs = [], []
    for j in range(12):
        name = f"job{j:02d}"
        pgs.append(build_pod_group(name, min_member=1))
        for t in range(4):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    node_selector={"disk": "ssd"} if j % 2 else None,
                )
            )
    from kube_batch_tpu.testing import build_cluster

    cluster = build_cluster(pods, nodes, pgs, [build_queue("default")])
    ssn = open_session(FakeCache(cluster), _tiers())
    cold = _encode(ssn)
    warm = _encode(ssn)
    _assert_arrays_equal(cold, warm, "selector")
    # churn one node into a new signature group
    ssn.nodes["n001"].set_node(
        build_node(
            "n001",
            build_resource_list(cpu=4, memory="8Gi", pods=20),
            labels={"disk": "nvme", "zone": "z9"},
        )
    )
    churn = _encode(ssn)
    encode_cache.get().invalidate_all("test")
    _assert_arrays_equal(_encode(ssn), churn, "selector-churn")
    close_session(ssn)


# -- action-level placement parity (serial + mesh {1,2,4,8}) -----------------


def _run_action(cluster, action_args=None, env=None):
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        cache = FakeCache(cluster)
        ssn = open_session(cache, _tiers(), action_args)
        get_action("xla_allocate").execute(ssn)
        state = {
            t.uid: (t.status, t.node_name)
            for j in ssn.jobs.values()
            for d in j.task_status_index.values()
            for t in d.values()
        }
        binds = dict(cache.binder.binds)
        close_session(ssn)
        return state, binds
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize("mesh", [None, 1, 2, 4, 8])
def test_placements_identical_cache_on_vs_off(mesh):
    """The acceptance pin: warm-path placements bind-for-bind identical
    to the cache-off path — serial-eligible snapshot, XLA twin, and the
    mesh rungs at {1,2,4,8} devices."""
    args = {"xla_allocate": {"mesh": f"cpu:{mesh}"}} if mesh else None
    make = lambda: synthetic(300, 64)  # noqa: E731
    state_off, binds_off = _run_action(make(), args, env={"KBT_ENCODE_CACHE": "0"})
    # cache on, twice (second run hits the per-object memos)
    state_on, binds_on = _run_action(make(), args, env={"KBT_ENCODE_CACHE": "1"})
    state_on2, binds_on2 = _run_action(make(), args, env={"KBT_ENCODE_CACHE": "1"})
    assert binds_on == binds_off and binds_on2 == binds_off
    assert state_on == state_off and state_on2 == state_off


def test_serial_action_untouched_by_cache():
    """The serial allocate does not encode: cache on/off cannot differ."""
    make = lambda: synthetic(120, 16)  # noqa: E731
    results = []
    for flag in ("0", "1"):
        os.environ["KBT_ENCODE_CACHE"] = flag
        cache = FakeCache(make())
        ssn = open_session(cache, _tiers())
        get_action("allocate").execute(ssn)
        results.append(dict(cache.binder.binds))
        close_session(ssn)
    assert results[0] == results[1]


# -- chaos: encode.cache fault + churn with the mutation detector on ---------


@pytest.mark.chaos
def test_encode_cache_fault_and_churn_binds_identical(monkeypatch):
    """Fire `encode.cache` mid-run and churn nodes between cycles with
    the mutation detector on: binds over the whole run must equal the
    cache-off twin's, and the fault must drop the cache (cold encode)."""
    monkeypatch.setenv("KBT_CACHE_MUTATION_DETECTOR", "1")
    monkeypatch.setenv("KBT_MIN_DEVICE_PAIRS", "0")

    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler

    def drive(cache_flag: str, arm_fault: bool):
        monkeypatch.setenv("KBT_ENCODE_CACHE", cache_flag)
        encode_cache.get().invalidate_all("test")
        faults.registry.reset()
        store = ClusterStore()
        store.create_queue(build_queue("default"))
        for i in range(8):
            store.create_node(
                build_node(
                    f"n{i}", build_resource_list(cpu=16, memory="32Gi", pods=64)
                )
            )
        cache = SchedulerCache(store)
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            conf = os.path.join(tmp, "conf.yaml")
            with open(conf, "w", encoding="utf-8") as fh:
                fh.write('actions: "enqueue, xla_allocate"\n' + CONF)
            sched = Scheduler(cache, scheduler_conf=conf, schedule_period=0.01)
            for cycle in range(4):
                for m in range(4):
                    store.create_pod(
                        build_pod(
                            name=f"c{cycle}-p{m}", group_name=f"g{cycle}",
                            req=build_resource_list(cpu=1, memory="512Mi"),
                        )
                    )
                store.create_pod_group(build_pod_group(f"g{cycle}", min_member=4))
                if arm_fault and cycle == 2:
                    faults.registry.arm("encode.cache", count=1)
                if cycle == 2:
                    # node churn between cycles: label flip through the
                    # store (the real watch-event path -> dirty feed)
                    n = store.get("nodes", "n0")
                    import dataclasses

                    store.update("nodes", dataclasses.replace(
                        n, metadata=dataclasses.replace(
                            n.metadata, labels={"churn": "1"}
                        )
                    ))
                sched.run_once()
        binds = {
            key: pod.node_name
            for key, pod in (
                (f"{p.namespace}/{p.name}", p) for p in store.list("pods")
            )
        }
        return binds

    binds_off = drive("0", arm_fault=False)
    fired0 = metrics.fault_injections.value({"point": "encode.cache"})
    binds_on = drive("1", arm_fault=True)
    fired1 = metrics.fault_injections.value({"point": "encode.cache"})
    assert binds_on == binds_off
    assert all(v for v in binds_on.values()), "unbound pods left behind"
    assert fired1 == fired0 + 1, "encode.cache fault did not fire"


# -- dirty feed + metrics ----------------------------------------------------


def test_dirty_feed_drops_entries_and_meters():
    ec = encode_cache.get()
    ssn = open_session(FakeCache(multi_queue(60, 8)), _tiers())
    _encode(ssn)
    assert ec._node_static, "node memo empty after encode"
    name = next(iter(ec._node_static))
    v0 = ec.version
    before = metrics.encode_cache_invalidations.value({"reason": "nodes"})
    encode_cache.note_store_event("nodes", name)
    assert name not in ec._node_static
    assert ec.version == v0 + 1
    assert metrics.encode_cache_invalidations.value({"reason": "nodes"}) == before + 1
    close_session(ssn)


def test_warm_fraction_metric_set():
    ssn = open_session(FakeCache(multi_queue(60, 8)), _tiers())
    _encode(ssn)
    _encode(ssn)
    assert metrics.encode_warm_fraction.value() > 0.5
    assert metrics.encode_cache_hits.value() > 0
    close_session(ssn)


def test_disabled_cache_is_inert():
    os.environ["KBT_ENCODE_CACHE"] = "0"
    ec = encode_cache.get()
    ec.invalidate_all("test")
    ssn = open_session(FakeCache(multi_queue(60, 8)), _tiers())
    _encode(ssn)
    _encode(ssn)
    assert ec._task_block is None and not ec._node_static
    close_session(ssn)


# -- tensor arena ------------------------------------------------------------


def test_arena_reuse_and_row_delta():
    import jax  # noqa: F401  (device path)

    arena = encode_cache.TensorArena()
    host = np.arange(32.0).reshape(8, 4)
    d1 = arena.upload("node_idle", host)
    assert arena.full_uploads == 1
    # identical content, different object -> buffer reuse, no upload
    d2 = arena.upload("node_idle", host.copy())
    assert arena.reuses == 1 and d2 is d1
    # one changed row -> in-place row scatter, not a full transfer
    churn = host.copy()
    churn[3] = [100.0, 101.0, 102.0, 103.0]
    d3 = arena.upload("node_idle", churn)
    assert arena.row_updates == 1 and arena.rows_uploaded == 1
    np.testing.assert_array_equal(np.asarray(d3), churn)
    # many changed rows -> full re-upload
    big = churn * 7.0
    d4 = arena.upload("node_idle", big)
    assert arena.full_uploads == 2
    np.testing.assert_array_equal(np.asarray(d4), big)
    # shape change -> fresh buffer
    grown = np.ones((16, 4))
    d5 = arena.upload("node_idle", grown)
    assert arena.full_uploads == 3
    np.testing.assert_array_equal(np.asarray(d5), grown)


def test_arena_device_view_passthrough():
    arena = encode_cache.TensorArena()
    arrays = {
        "node_idle": np.ones((8, 4)),
        "compat": np.ones((2, 3), bool),
        "node_gid": np.zeros(8, np.int32),  # unmanaged: passes through
    }
    view = arena.device_view(arrays)
    assert view["node_gid"] is arrays["node_gid"]
    np.testing.assert_array_equal(np.asarray(view["node_idle"]), arrays["node_idle"])
    np.testing.assert_array_equal(np.asarray(view["compat"]), arrays["compat"])
