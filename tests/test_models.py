"""Sanity checks on the synthetic workload generators (the bench configs
of BASELINE.md) — counts, queues, scalar resources, determinism."""

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.models import (
    GPU,
    TPU,
    gang_example,
    multi_queue,
    multi_tenant_ml,
    preempt_mix,
    synthetic,
)


def pending_count(cluster) -> int:
    return sum(
        len(j.task_status_index.get(TaskStatus.PENDING, {}))
        for j in cluster.jobs.values()
    )


def test_gang_example_shape():
    c = gang_example()
    assert len(c.nodes) == 3
    assert pending_count(c) == 3
    (job,) = c.jobs.values()
    assert job.min_available == 3


def test_synthetic_shape():
    c = synthetic(200, 20)
    assert len(c.nodes) == 20
    assert pending_count(c) == 200
    assert len(c.queues) == 1


def test_multi_queue_shape():
    c = multi_queue(400, 40, n_queues=4, tasks_per_job=8)
    assert len(c.queues) == 4
    assert pending_count(c) == 400
    queues_used = {j.queue for j in c.jobs.values()}
    assert queues_used == {f"q{i}" for i in range(4)}


def test_preempt_mix_has_residents():
    c = preempt_mix(500, 50, tasks_per_job=10)
    assert pending_count(c) == 500
    running = sum(
        len(j.task_status_index.get(TaskStatus.RUNNING, {}))
        for j in c.jobs.values()
    )
    releasing = sum(
        len(j.task_status_index.get(TaskStatus.RELEASING, {}))
        for j in c.jobs.values()
    )
    assert running + releasing == 25  # one victim per 2 nodes
    assert any(n.used.milli_cpu > 0 for n in c.nodes.values())


def test_multi_tenant_ml_scalars():
    c = multi_tenant_ml(n_jobs=10, n_nodes=20, n_queues=5)
    assert len(c.queues) == 5
    accels = set()
    for j in c.jobs.values():
        for t in j.task_status_index.get(TaskStatus.PENDING, {}).values():
            accels.update(t.resreq.scalars)
    assert accels <= {GPU, TPU} and accels
    gpu_nodes = [n for n in c.nodes.values() if GPU in n.allocatable.scalars]
    tpu_nodes = [n for n in c.nodes.values() if TPU in n.allocatable.scalars]
    assert gpu_nodes and tpu_nodes


def test_generators_deterministic():
    a, b = synthetic(100, 10, seed=5), synthetic(100, 10, seed=5)
    assert sorted(a.jobs) == sorted(b.jobs)
    ta = {t.uid: t.resreq.milli_cpu for j in a.jobs.values() for t in j.tasks.values()}
    tb = {t.uid: t.resreq.milli_cpu for j in b.jobs.values() for t in j.tasks.values()}
    assert ta == tb
