"""Tier-1 tests for the domain-aware static analysis suite
(kube_batch_tpu.analysis) and the stdlib lint checks it rides with
(hack/verify.py).

Each analyzer (A1 lock-discipline, A2 JAX hazards, A3 registry
consistency, A4 snapshot escape) is proven on a seeded-violation
fixture — source strings with exactly the defect class the analyzer
exists to catch — plus its negative twin (the compliant spelling must
NOT fire). The live tree runs as a smoke: the committed baseline must
leave zero unsuppressed findings, so `hack/verify.py` stays green.
"""

from __future__ import annotations

import ast
import importlib.util
import json
import os
import subprocess
import sys

import pytest

from kube_batch_tpu.analysis import (
    SourceFile,
    apply_baseline,
    load_baseline,
    load_tree,
    run_suite,
)
from kube_batch_tpu.analysis import (
    jax_hazards,
    lock_discipline,
    lock_order,
    protocol,
    registry_consistency,
    snapshot_escape,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sf(path: str, source: str) -> SourceFile:
    return SourceFile(path, source, ast.parse(source, path))


def codes(findings) -> list[str]:
    return [f.code for f in findings]


# -- A1: lock discipline -----------------------------------------------------

A1_FIXTURE = '''
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0        #: guarded_by _lock
        self._items = {}     #: guarded_by _lock

    def bad(self):
        self._seq += 1       # VIOLATION: no lock held

    def good(self):
        with self._lock:
            self._seq += 1
            return self._items.get(1)

    def _bump_locked(self):
        self._seq += 1       # exempt: _locked suffix

    @assume_locked
    def _peek(self):
        return self._items   # exempt: assume_locked marker

    def nested_ok(self):
        with self._lock:
            def inner():
                return self._seq   # lexically under the with: ok
            return inner()
'''


def test_lock_discipline_fires_on_unlocked_access():
    findings = lock_discipline.analyze([sf("kube_batch_tpu/x/hub.py", A1_FIXTURE)])
    assert codes(findings) == ["KBT-L001"]
    f = findings[0]
    assert f.symbol == "Hub.bad._seq"
    assert "_lock" in f.message


def test_lock_discipline_seed_map_applies_to_real_paths():
    src = (
        "import threading\n"
        "class RateLimitingQueue:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self._heap = []\n"
        "    def peek(self):\n"
        "        return self._heap[0]\n"
    )
    findings = lock_discipline.analyze([sf("kube_batch_tpu/utils/workqueue.py", src)])
    assert codes(findings) == ["KBT-L001"]
    assert findings[0].symbol == "RateLimitingQueue.peek._heap"


def test_lock_discipline_unknown_lock_annotation():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self._x = 1  #: guarded_by _mutex\n"
    )
    findings = lock_discipline.analyze([sf("kube_batch_tpu/x/c.py", src)])
    assert codes(findings) == ["KBT-L002"]


# -- A2: JAX hazards ---------------------------------------------------------

A2_FIXTURE = '''
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

@partial(jax.jit, static_argnames=("flag",))
def solve(x, flag):
    if flag:                       # static arg: ok
        x = x + 1
    if x is None:                  # identity: ok (fresh/resume dispatch)
        x = jnp.zeros(())
    v = x.item()                   # VIOLATION J001 host sync
    print("trace", v)              # VIOLATION J003 bare print
    y = np.asarray(x)              # VIOLATION J001 np materialization
    if jnp.any(x > 0):             # VIOLATION J002 truth test on traced
        y = y + 1
    return helper(y)

def helper(y):
    return float(y)                # VIOLATION J001 via call closure

def host_pack(a):
    return np.asarray(a).item()    # not jit-reachable: silent
'''


def test_jax_hazards_fire_in_jit_scope_only():
    findings = jax_hazards.analyze([sf("kube_batch_tpu/ops/fix.py", A2_FIXTURE)])
    got = sorted(codes(findings))
    assert got == ["KBT-J001", "KBT-J001", "KBT-J001", "KBT-J002", "KBT-J003"]
    # the host-side function stayed silent
    assert not any("host_pack" in f.symbol for f in findings)
    # the call-closure reached helper()
    assert any(f.symbol.startswith("helper.") for f in findings)


def test_jax_hazards_scope_is_ops_and_parallel():
    findings = jax_hazards.analyze([sf("kube_batch_tpu/cache/fix.py", A2_FIXTURE)])
    assert findings == []


J004_FIXTURE = '''
import numpy as np
from kube_batch_tpu.api.numerics import comparison_dtype

def share_bad(a, b):
    return float(np.float64(a) / np.float64(b))   # VIOLATION x2

def share_ok(a, b):
    dt = comparison_dtype()
    if dt is np.float64:                          # identity consult: ok
        return a / b
    return float(dt(a) / dt(b))
'''


def test_dtype_policy_fires_in_plugins_not_kernels():
    findings = jax_hazards.analyze([sf("kube_batch_tpu/plugins/fix.py", J004_FIXTURE)])
    # two literals on one line share (path, line, code, symbol): one finding
    assert codes(findings) == ["KBT-J004"]
    assert all(f.symbol.startswith("share_bad") for f in findings)
    # kernels pin f32 by contract; out of J004 scope
    assert jax_hazards.analyze([sf("kube_batch_tpu/ops/fix2.py", J004_FIXTURE)]) == []


# -- A3: registry consistency ------------------------------------------------

FAULTS_FIXTURE = (
    "POINTS = (\n"
    '    "solve.xla",\n'
    '    "bind.write",\n'
    '    "evict.write",\n'
    '    "lease.renew",\n'
    ")\n"
)

FIRER_FIXTURE = '''
from kube_batch_tpu import faults, metrics

def go(op):
    if faults.should_fire("solve.xla"):
        raise RuntimeError
    if faults.should_fire(f"{op}.write"):      # wildcard: bind./evict.write
        raise RuntimeError
    if faults.should_fire("solve.typo"):       # VIOLATION R001
        raise RuntimeError
    metrics.register_fault_injection("x")
    metrics.register_nonexistent("x")          # VIOLATION R003
'''

METRICS_FIXTURE = (
    "def register_fault_injection(point):\n"
    "    pass\n"
)


def _a3_files():
    return [
        sf("kube_batch_tpu/faults/__init__.py", FAULTS_FIXTURE),
        sf("kube_batch_tpu/metrics/__init__.py", METRICS_FIXTURE),
        sf("kube_batch_tpu/worker.py", FIRER_FIXTURE),
    ]


def test_registry_fault_points_both_directions(tmp_path):
    findings = registry_consistency.analyze(
        _a3_files(), repo=str(tmp_path), runbook="deployment/README.md"
    )
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f)
    # the typo fires R001; lease.renew is registered but never fired (R002)
    assert [f.symbol for f in by_code["KBT-R001"]] == ["point:solve.typo"]
    assert [f.symbol for f in by_code["KBT-R002"]] == ["point:lease.renew"]
    # the f-string wildcard credited bind.write AND evict.write
    fired_r002 = {f.symbol for f in by_code["KBT-R002"]}
    assert "point:bind.write" not in fired_r002
    assert "point:evict.write" not in fired_r002
    assert [f.symbol for f in by_code["KBT-R003"]] == ["metric:register_nonexistent"]


ENV_READER_FIXTURE = (
    "import os\n"
    'A = os.environ.get("KBT_ALPHA", "")\n'
    'B = os.environ["KBT_BETA"]\n'
    'ENV = "KBT_GAMMA"\n'
)

RUNBOOK_FIXTURE = (
    "# runbook\n\n"
    "| variable | default | meaning |\n"
    "|---|---|---|\n"
    "| `KBT_ALPHA` | off | alpha |\n"
    "| `KBT_GAMMA` | off | gamma |\n"
    "| `KBT_DEAD` | off | nobody reads me |\n"
)


def test_registry_env_table_both_directions(tmp_path):
    (tmp_path / "deployment").mkdir()
    (tmp_path / "deployment" / "README.md").write_text(RUNBOOK_FIXTURE)
    files = [sf("kube_batch_tpu/knobs.py", ENV_READER_FIXTURE)]
    findings = registry_consistency.analyze(files, repo=str(tmp_path))
    syms = {f.code: f.symbol for f in findings}
    assert syms.get("KBT-R004") == "env:KBT_BETA"  # read, undocumented
    assert syms.get("KBT-R005") == "env:KBT_DEAD"  # documented, dead
    assert len(findings) == 2  # ALPHA direct + GAMMA via ALL-CAPS const are fine


# -- A4: snapshot escape -----------------------------------------------------

A4_FIXTURE = '''
class BadAction:
    def execute(self, ssn):
        for job in ssn.jobs.values():
            for task in job.tasks.values():
                task.node_name = "n0"          # VIOLATION S001
        node = ssn.nodes.get("n0")
        node.add_task(task)                    # VIOLATION S002

class GoodAction:
    def execute(self, ssn):
        stmt = ssn.statement()
        for job in ssn.jobs.values():
            for task in job.tasks.values():
                ssn.allocate(task, "n0")       # sanctioned API
        stmt.commit()
'''


def test_snapshot_escape_fires_on_direct_mutation():
    findings = snapshot_escape.analyze([sf("kube_batch_tpu/actions/fix.py", A4_FIXTURE)])
    assert sorted(codes(findings)) == ["KBT-S001", "KBT-S002"]
    assert {f.symbol for f in findings} == {
        "BadAction.execute.node_name",
        "BadAction.execute.add_task",
    }


def test_snapshot_escape_scope_is_plugins_and_actions():
    findings = snapshot_escape.analyze([sf("kube_batch_tpu/framework/fix.py", A4_FIXTURE)])
    assert findings == []


# -- baseline ----------------------------------------------------------------

def test_baseline_requires_reasons_and_flags_stale(tmp_path):
    bl_file = tmp_path / "lint-baseline.toml"
    bl_file.write_text(
        "[[suppress]]\n"
        'code = "KBT-L001"\n'
        'path = "kube_batch_tpu/x/hub.py"\n'
        'symbol = "Hub.bad._seq"\n'
        'reason = "seeded fixture, intentionally kept"\n'
        "\n"
        "[[suppress]]\n"
        'code = "KBT-J003"\n'
        'path = "kube_batch_tpu/x/hub.py"\n'
        'reason = ""\n'          # reason-less -> KBT-B001
    )
    bl = load_baseline(str(bl_file), str(tmp_path))
    assert [e.code for e in bl.errors] == ["KBT-B001"]

    findings = lock_discipline.analyze([sf("kube_batch_tpu/x/hub.py", A1_FIXTURE)])
    kept, suppressed, stale = apply_baseline(findings, bl)
    assert kept == []
    assert len(suppressed) == 1
    # the J003 entry matched nothing -> stale (KBT-B002)
    assert [s.code for s in stale] == ["KBT-B002"]


def test_baseline_unparseable_line_is_loud(tmp_path):
    bl_file = tmp_path / "bl.toml"
    bl_file.write_text("[[suppress]]\ncode = unquoted\n")
    bl = load_baseline(str(bl_file), str(tmp_path))
    assert any("unparseable" in e.message for e in bl.errors)


# -- the stdlib lint (hack/verify.py) ---------------------------------------

def _verify_mod():
    spec = importlib.util.spec_from_file_location(
        "kbt_hack_verify", os.path.join(REPO, "hack", "verify.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize(
    "source,expect",
    [
        ("import os\n", "F401"),
        ("try:\n    pass\nexcept:\n    pass\n", "E722"),
        ("x = 1\nif x == None:\n    pass\n", "E711"),
        ("x = 1\nif None == x:\n    pass\n", "E711"),  # the left-side gap
        ("x = 1\nif None != x:\n    pass\n", "E711"),
        ("def f(a=[]):\n    return a\n", "B006"),
        ("s = f'no placeholder'\n", "F541"),
    ],
)
def test_stdlib_lint_checks_fire(source, expect, tmp_path):
    verify = _verify_mod()
    lint = verify._Lint("x.py", ast.parse(source), source)
    msgs = [m for _, m in lint.problems]
    assert any(m.startswith(expect) for m in msgs), (source, msgs)


def test_stdlib_lint_none_equality_not_double_counted():
    verify = _verify_mod()
    source = "x = 1\nif None == x == None:\n    pass\n"
    lint = verify._Lint("x.py", ast.parse(source), source)
    # two comparison ops, two problems — not four
    assert [m for _, m in lint.problems if m.startswith("E711")] != []
    assert len([m for _, m in lint.problems if m.startswith("E711")]) == 2


def test_stdlib_lint_is_none_clean():
    verify = _verify_mod()
    source = "x = 1\nif x is None:\n    pass\n"
    lint = verify._Lint("x.py", ast.parse(source), source)
    assert lint.problems == []


# -- live tree smoke ---------------------------------------------------------

def test_live_tree_is_clean_under_committed_baseline():
    findings = run_suite(REPO)
    bl = load_baseline(os.path.join(REPO, "hack", "lint-baseline.toml"), REPO)
    assert bl.errors == [], [e.message for e in bl.errors]
    kept, suppressed, stale = apply_baseline(findings, bl)
    assert kept == [], "unsuppressed findings:\n" + "\n".join(
        f.render() for f in kept
    )
    assert stale == [], "stale baseline entries:\n" + "\n".join(
        f.render() for f in stale
    )
    # the baseline is doing real work, not vacuously empty
    assert suppressed, "expected the committed baseline to cover known findings"


def test_live_tree_fault_and_env_registries_fully_covered():
    files = load_tree(REPO)
    findings = registry_consistency.analyze(files, repo=REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- D codes: lock order / blocking-under-lock -------------------------------

ABBA_FIXTURE = """
import threading

class A:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def ba(self):
        with self._lb:
            with self._la:
                pass
"""


def test_lock_order_abba_cycle_fires():
    findings = lock_order.analyze([sf("kube_batch_tpu/x/abba.py", ABBA_FIXTURE)])
    assert codes(findings) == ["KBT-D001"]
    assert findings[0].symbol == "cycle:A._la<->A._lb"
    assert "re-nest" in findings[0].message


def test_lock_order_consistent_nesting_is_clean():
    src = ABBA_FIXTURE.replace("self._lb:\n            with self._la",
                               "self._la:\n            with self._lb")
    assert lock_order.analyze([sf("kube_batch_tpu/x/ok.py", src)]) == []


D002_FIXTURE = """
import os
import threading

class J:
    def __init__(self):
        self._lock = threading.Lock()
        self._fd = 3

    def bad(self):
        with self._lock:
            os.fsync(self._fd)

    def good(self):
        with self._lock:
            fd = self._fd
        os.fsync(fd)
"""


def test_lock_order_blocking_under_lock_fires_held_side_only():
    findings = lock_order.analyze([sf("kube_batch_tpu/x/j.py", D002_FIXTURE)])
    assert codes(findings) == ["KBT-D002"]
    assert findings[0].symbol == "J.bad.os.fsync"


def test_lock_order_condition_wait_on_held_lock_exempt():
    src = (
        "import threading\n"
        "class H:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def waiter(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait()\n"
    )
    assert lock_order.analyze([sf("kube_batch_tpu/x/h.py", src)]) == []


def test_lock_order_interprocedural_charges_locked_caller():
    src = (
        "import threading, time\n"
        "class K:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self._flush()\n"
        "    def _flush(self):\n"
        "        time.sleep(0.1)\n"
    )
    findings = lock_order.analyze([sf("kube_batch_tpu/x/k.py", src)])
    assert codes(findings) == ["KBT-D002"]
    assert findings[0].symbol == "K.outer.time.sleep"


def test_lock_order_crosses_collaborator_classes():
    src = (
        "import os, threading\n"
        "class Journal:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def write(self):\n"
        "        os.fsync(1)\n"
        "class Cache:\n"
        "    def __init__(self):\n"
        "        self._mutex = threading.Lock()\n"
        "        self._j = Journal()\n"
        "    def bind(self):\n"
        "        with self._mutex:\n"
        "            self._j.write()\n"
    )
    findings = lock_order.analyze([sf("kube_batch_tpu/x/c2.py", src)])
    assert codes(findings) == ["KBT-D002"]
    assert findings[0].symbol == "Cache.bind.os.fsync"
    assert "Journal.write" in findings[0].message


# -- runtime lock-order witness (dynamic half of KBT-D001) -------------------


def test_lock_order_witness_flags_abba_reversal():
    import threading

    from kube_batch_tpu.utils.locking import LockOrderWitness

    w = LockOrderWitness()
    la = w.wrap("A", threading.Lock())
    lb = w.wrap("B", threading.Lock())

    def a_then_b():
        with la:
            with lb:
                pass

    def b_then_a():
        with lb:
            with la:
                pass

    # sequential threads: both orders are observed without ever actually
    # deadlocking — exactly the latent ABBA the witness exists to catch
    for fn, name in ((a_then_b, "t-ab"), (b_then_a, "t-ba")):
        t = threading.Thread(target=fn, name=name)
        t.start()
        t.join()
    assert len(w.violations) == 1
    assert "t-ab" in w.violations[0] and "t-ba" in w.violations[0]
    with pytest.raises(AssertionError, match="reversal"):
        w.assert_clean()


def test_lock_order_witness_consistent_order_and_nonlifo_release_clean():
    import threading

    from kube_batch_tpu.utils.locking import LockOrderWitness

    w = LockOrderWitness()
    la = w.wrap("A", threading.Lock())
    lb = w.wrap("B", threading.Lock())
    for _ in range(3):
        with la:
            with lb:
                pass
    # non-LIFO release is legal for plain locks and must not corrupt the
    # held stack
    la.acquire()
    lb.acquire()
    la.release()
    lb.release()
    with la:
        with lb:
            pass
    assert w.violations == []
    w.assert_clean()


def test_lock_order_witness_reentrant_rlock_is_not_a_self_edge():
    import threading

    from kube_batch_tpu.utils.locking import LockOrderWitness

    w = LockOrderWitness()
    mu = w.wrap("M", threading.RLock())
    with mu:
        with mu:
            pass
    assert w.violations == []


@pytest.mark.chaos
def test_lock_order_witness_clean_on_live_bind_path(tmp_path):
    """Wrap the real cache/journal/store locks and drive a concurrent
    bind workload through the write pool: the dynamic acquisition graph
    must stay reversal-free (the static KBT-D001 sees the lexical graph;
    this is the dispatch-through-indirection half)."""
    import threading
    import time

    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.recovery import WriteIntentJournal
    from kube_batch_tpu.testing import (
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )
    from kube_batch_tpu.utils.locking import LockOrderWitness

    store = ClusterStore()
    store.create_queue(build_queue("default"))
    for i in range(4):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=32))
        )
    for g in range(2):
        store.create_pod_group(build_pod_group(f"g{g}", min_member=8))
        for m in range(8):
            store.create_pod(
                build_pod(
                    name=f"g{g}-p{m}", group_name=f"g{g}",
                    req=build_resource_list(cpu=1, memory="256Mi"),
                )
            )
    journal = WriteIntentJournal(str(tmp_path / "j.wal"))
    cache = SchedulerCache(store, journal=journal)

    w = LockOrderWitness()
    cache._mutex = w.wrap("SchedulerCache._mutex", cache._mutex)
    journal._lock = w.wrap("WriteIntentJournal._lock", journal._lock)
    store._lock = w.wrap("ClusterStore._lock", store._lock)
    store._dispatch_lock = w.wrap("ClusterStore._dispatch_lock", store._dispatch_lock)

    cache.run()
    try:
        jobs = sorted(cache.jobs.values(), key=lambda j: j.name)
        assert len(jobs) == 2

        def bind_job(job, salt):
            for i, task in enumerate(sorted(job.tasks.values(), key=lambda t: t.uid)):
                cache.bind(task, f"n{(i + salt) % 4}")

        def read_side():
            for _ in range(20):
                store.list("pods")
                journal.outstanding()
                with cache._mutex:
                    len(cache.nodes)
                time.sleep(0.001)

        threads = [
            threading.Thread(target=bind_job, args=(jobs[0], 0), name="bind-0"),
            threading.Thread(target=bind_job, args=(jobs[1], 1), name="bind-1"),
            threading.Thread(target=read_side, name="reader"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(p.node_name for p in store.list("pods")):
                break
            time.sleep(0.02)
        assert all(p.node_name for p in store.list("pods"))
    finally:
        cache.stop()
        journal.close()
    # the drive actually nested acquisitions (store event dispatch runs
    # the cache mirror handlers, so the witness saw real edges) and the
    # observed dynamic order has no reversal
    assert w._edges, "expected the bind workload to nest lock acquisitions"
    w.assert_clean()


# -- CLI ---------------------------------------------------------------------

def test_cli_json_and_exit_codes():
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    payload = json.loads(res.stdout.strip().splitlines()[-1])
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert payload["suppressed"] > 0


def test_cli_explain():
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--explain", "KBT-L001"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert res.returncode == 0
    assert "guarded" in res.stdout


def test_cli_reasonless_baseline_entry_fails_the_gate(tmp_path):
    bad = tmp_path / "bl.toml"
    bad.write_text(
        "[[suppress]]\n"
        'code = "KBT-L001"\n'
        'path = "kube_batch_tpu/server.py"\n'
        'reason = ""\n'
    )
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--strict",
         "--baseline", str(bad)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert res.returncode == 1
    assert "KBT-B001" in res.stdout


def test_cli_no_baseline_reports_known_intentional_findings():
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert res.returncode == 1
    assert "KBT-" in res.stdout


# -- --prune -----------------------------------------------------------------

COMMITTED_BASELINE = os.path.join(REPO, "hack", "lint-baseline.toml")


def _run_prune(bl_path, *extra):
    return subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--prune",
         "--baseline", str(bl_path), *extra],
        cwd=REPO, capture_output=True, text=True,
    )


def test_cli_prune_drops_stale_entries_preserving_the_rest(tmp_path):
    committed = open(COMMITTED_BASELINE, encoding="utf-8").read()
    bl = tmp_path / "bl.toml"
    bl.write_text(
        committed.rstrip("\n")
        + "\n\n[[suppress]]\n"
        + 'code = "KBT-L001"\n'
        + 'path = "kube_batch_tpu/does/not/exist.py"\n'
        + 'reason = "stale on purpose: the file is gone"\n'
    )
    res = _run_prune(bl)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "pruned: KBT-L001 at kube_batch_tpu/does/not/exist.py" in res.stdout
    assert "1 stale entry dropped" in res.stdout
    # live entries survive byte-for-byte: preamble, reasons, ordering
    assert bl.read_text() == committed


def test_cli_prune_noop_leaves_baseline_byte_identical(tmp_path):
    committed = open(COMMITTED_BASELINE, encoding="utf-8").read()
    bl = tmp_path / "bl.toml"
    bl.write_text(committed)
    res = _run_prune(bl)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 stale entries dropped" in res.stdout
    assert bl.read_text() == committed


def test_cli_prune_requires_a_baseline():
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--prune",
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert res.returncode == 2


# -- A6: protocol lifecycles ---------------------------------------------------

C001_LEAKY = '''
from kube_batch_tpu.framework.session import close_session, open_session

def leaky(cache, tiers, args):
    ssn = open_session(cache, tiers, args)
    if not ssn.jobs:
        return None          # VIOLATION: ssn open on this exit path
    close_session(ssn)
    return True
'''

C001_CLEAN = '''
from kube_batch_tpu.framework.session import close_session, open_session

def clean(cache, tiers, args):
    ssn = open_session(cache, tiers, args)
    try:
        return len(ssn.jobs)
    finally:
        close_session(ssn)
'''

C001_STMT_LEAKY = '''
def bail_without_discard(ssn, tasks):
    stmt = ssn.statement()
    for t in tasks:
        if not t.ok:
            return False     # VIOLATION: neither commit nor discard
    stmt.commit()
    return True
'''

C001_STMT_CLEAN = '''
def settled_everywhere(ssn, tasks, helper):
    stmt = ssn.statement()
    for t in tasks:
        helper(ssn, stmt, t)  # borrow: passing by argument is not escape
        if not t.ok:
            stmt.discard()
            return False
    stmt.commit()
    return True
'''


def test_protocol_c001_session_leak_fires_and_clean_twin_does_not():
    findings = protocol.analyze([sf("kube_batch_tpu/x/leak.py", C001_LEAKY)])
    assert codes(findings) == ["KBT-C001"]
    assert "ssn" in findings[0].message
    assert protocol.analyze([sf("kube_batch_tpu/x/ok.py", C001_CLEAN)]) == []


def test_protocol_c001_statement_leak_fires_and_borrow_is_not_escape():
    findings = protocol.analyze([sf("kube_batch_tpu/x/stmt.py", C001_STMT_LEAKY)])
    assert codes(findings) == ["KBT-C001"]
    assert protocol.analyze([sf("kube_batch_tpu/x/ok.py", C001_STMT_CLEAN)]) == []


C002_DISPATCH = '''
def rogue(cache, task):
    cache.bind(task, "n1")
'''


def test_protocol_c002_dispatch_scope_is_the_statement_layer():
    findings = protocol.analyze([sf("kube_batch_tpu/plugins/rogue.py", C002_DISPATCH)])
    assert codes(findings) == ["KBT-C002"]
    # the same call inside an owning module is the implementation, not a bypass
    assert protocol.analyze(
        [sf("kube_batch_tpu/framework/statement.py", C002_DISPATCH)]
    ) == []


C002_BREAKER = '''
class Probe:
    def poke(self, breaker):
        breaker._transition("OPEN")
'''

C002_BREAKER_BAD_STATE = '''
class CircuitBreaker:
    def _step(self):
        self._transition("melted")
'''


def test_protocol_c002_breaker_transitions_stay_in_the_ladder():
    findings = protocol.analyze([sf("kube_batch_tpu/plugins/probe.py", C002_BREAKER)])
    assert codes(findings) == ["KBT-C002"]
    # inside the ladder with a declared state: fine
    ok = C002_BREAKER.replace("class Probe", "class CircuitBreaker").replace(
        '"OPEN"', '"open"'
    )
    assert protocol.analyze([sf("kube_batch_tpu/faults/ladder.py", ok)]) == []
    # inside the ladder but outside the declared alphabet: still flagged
    findings = protocol.analyze(
        [sf("kube_batch_tpu/faults/ladder.py", C002_BREAKER_BAD_STATE)]
    )
    assert codes(findings) == ["KBT-C002"]


C003_ORPHAN = '''
def orphan(journal, intents):
    journal.append_intents(intents)
    return None
'''

C003_PAIRED = '''
def paired(journal, cache, intents):
    seqs = journal.append_intents(intents)
    cache._submit_write(seqs)
    for s in seqs:
        journal.confirm(s)
'''

C003_CONFIRM_ONLY = '''
def confirm_strangers(journal, seqs):
    for s in seqs:
        journal.confirm(s)
'''


def test_protocol_c003_append_without_dispatch_or_confirm():
    findings = protocol.analyze([sf("kube_batch_tpu/x/j.py", C003_ORPHAN)])
    assert set(codes(findings)) == {"KBT-C003"}
    assert protocol.analyze([sf("kube_batch_tpu/x/ok.py", C003_PAIRED)]) == []


def test_protocol_c003_confirm_without_append_exempts_recovery():
    findings = protocol.analyze([sf("kube_batch_tpu/x/c.py", C003_CONFIRM_ONLY)])
    assert codes(findings) == ["KBT-C003"]
    # takeover legitimately confirms a dead leader's intents
    assert protocol.analyze(
        [sf("kube_batch_tpu/recovery/takeover_x.py", C003_CONFIRM_ONLY)]
    ) == []


C004_STALE_READ = '''
def stale(state, patches):
    state.invalidate("bound churn")
    state.apply_node_patches(patches)
'''

C004_REHARVESTED = '''
def reharvested(state, ssn, patches):
    state.invalidate("bound churn")
    state.adopt_full_cycle(ssn)
    state.apply_node_patches(patches)
'''


def test_protocol_c004_read_after_invalidate_needs_reharvest():
    findings = protocol.analyze([sf("kube_batch_tpu/x/s.py", C004_STALE_READ)])
    assert codes(findings) == ["KBT-C004"]
    assert protocol.analyze([sf("kube_batch_tpu/x/ok.py", C004_REHARVESTED)]) == []


C005_GAP = '''
def leaky_loop(trigger, stop, prepare, run):
    trigger.attach()
    prepare()
    try:
        run(stop)
    finally:
        trigger.detach()
'''

C005_TIGHT = '''
def tight_loop(trigger, stop, prepare, run):
    prepare()
    trigger.attach()
    try:
        run(stop)
    finally:
        trigger.detach()
'''

C005_CLASS_TEARDOWN = '''
class Consumer:
    def start(self):
        self.trigger.attach()

    def stop(self):
        self.trigger.detach()
'''


def test_protocol_c005_registration_gap_before_try_fires():
    findings = protocol.analyze([sf("kube_batch_tpu/x/loop.py", C005_GAP)])
    assert codes(findings) == ["KBT-C005"]
    assert protocol.analyze([sf("kube_batch_tpu/x/ok.py", C005_TIGHT)]) == []


def test_protocol_c005_class_teardown_pairing_is_clean():
    assert protocol.analyze(
        [sf("kube_batch_tpu/x/consumer.py", C005_CLASS_TEARDOWN)]
    ) == []
