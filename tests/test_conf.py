"""Conf schema/loader tests (reference util_test.go:27 pattern) plus
regression tests for the round-1 defects (VERDICT weak #3/#6/#7)."""

import pytest

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu.api.helpers import min_resource
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus, validate_status_update
from kube_batch_tpu.conf import (
    DEFAULT_SCHEDULER_CONF,
    load_scheduler_conf,
    parse_scheduler_conf,
)
from kube_batch_tpu.testing import build_resource_list


class TestConfParse:
    def test_default_conf(self):
        actions_list, tiers, action_args = load_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        assert action_args == {}
        assert [a.name for a in actions_list] == ["allocate", "backfill"]
        assert len(tiers) == 2
        assert [p.name for p in tiers[0].plugins] == ["priority", "gang"]
        assert [p.name for p in tiers[1].plugins] == [
            "drf",
            "predicates",
            "proportion",
            "nodeorder",
        ]

    def test_enable_flags_default_true(self):
        conf = parse_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        for tier in conf.tiers:
            for option in tier.plugins:
                assert option.enabled_job_order is True
                assert option.enabled_predicate is True

    def test_explicit_flag_respected(self):
        conf = parse_scheduler_conf(
            """
actions: "allocate"
tiers:
- plugins:
  - name: gang
    enableJobOrder: false
    arguments:
      foo: "7"
"""
        )
        option = conf.tiers[0].plugins[0]
        assert option.enabled_job_order is False
        assert option.enabled_job_ready is True
        assert option.arguments == {"foo": "7"}

    def test_action_arguments_parsed(self):
        conf = parse_scheduler_conf(
            """
actions: "enqueue, xla_allocate, backfill"
actionArguments:
  xla_allocate:
    mesh: auto
tiers:
- plugins:
  - name: gang
"""
        )
        assert conf.action_arguments == {"xla_allocate": {"mesh": "auto"}}

    def test_unknown_action_raises(self):
        with pytest.raises(ValueError):
            load_scheduler_conf('actions: "no-such-action"')

    def test_full_pipeline_order(self):
        actions_list, _, _ = load_scheduler_conf(
            'actions: "enqueue, reclaim, allocate, backfill, preempt"'
        )
        assert [a.name for a in actions_list] == [
            "enqueue",
            "reclaim",
            "allocate",
            "backfill",
            "preempt",
        ]


class TestRound1Fixes:
    def test_build_resource_list_kwarg_translation(self):
        rl = build_resource_list(cpu=1, nvidia__com__gpu=2)
        assert rl == {"cpu": 1.0, "nvidia.com/gpu": 2.0}
        rl = build_resource_list(google__com__tpu=8)
        assert rl == {"google.com/tpu": 8.0}

    def test_from_resource_list_ignores_non_scalar_names(self):
        r = Resource.from_resource_list(
            {"cpu": 1, "ephemeral-storage": 10_000_000, "nvidia.com/gpu": 2}
        )
        assert r.scalars == {"nvidia.com/gpu": 2000.0}

    def test_sub_scalar_onto_scalar_free_receiver_raises(self):
        # Go parity: LessEqual returns false when the subtrahend has a
        # scalar entry and the receiver has none (resource_info.go:264-267),
        # so Sub panics before its (dead) nil-map early return — no
        # negative residue can appear on a scalar-free receiver.
        r = Resource(milli_cpu=1000, memory=1000)
        with pytest.raises(ValueError):
            r.sub(Resource(milli_cpu=500, memory=500, scalars={"g": 5}))
        assert r.scalars == {}

    def test_min_resource_drops_scalars_when_either_side_nil(self):
        l = Resource(milli_cpu=100, memory=100, scalars={"g": 5})
        r = Resource(milli_cpu=200, memory=50)
        out = min_resource(l, r)
        assert out.milli_cpu == 100 and out.memory == 50
        assert out.scalars == {}
        both = min_resource(l, Resource(milli_cpu=0, memory=0, scalars={"g": 2}))
        assert both.scalars == {"g": 2}

    def test_validate_status_update_rejects_terminal_reentry(self):
        with pytest.raises(ValueError):
            validate_status_update(TaskStatus.SUCCEEDED, TaskStatus.ALLOCATED)
        # Normal flow stays permitted.
        validate_status_update(TaskStatus.PENDING, TaskStatus.ALLOCATED)
        validate_status_update(TaskStatus.ALLOCATED, TaskStatus.BINDING)
        validate_status_update(TaskStatus.RUNNING, TaskStatus.RELEASING)

    def test_fake_binder_signals_once_per_bind(self):
        from kube_batch_tpu.testing import FakeBinder, build_pod

        binder = FakeBinder()
        binder.bind(build_pod(name="a"), "n1")
        binder.bind(build_pod(name="b"), "n2")
        assert binder.channel.get_nowait() == "default/a"
        assert binder.channel.get_nowait() == "default/b"
        assert binder.channel.empty()
