"""Crash-consistent failover (ISSUE 3 tentpole): the bind-intent
journal, takeover reconciliation, the cycle deadline budget, and the
bounded-staleness watch hardening — driven end to end.

The headline chaos e2e kills a leader mid-``bind_many`` (the write pool
dies between the journal's append-before-dispatch and the store writes)
and asserts the standby's reconciled final placements are bind-for-bind
equal to an uninterrupted run: zero lost binds, zero duplicate binds,
cache-mutation detector on throughout (conftest arms it suite-wide).
"""

from __future__ import annotations

import json
import time

import pytest

from kube_batch_tpu import faults, metrics
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.cache import StoreBinder
from kube_batch_tpu.faults.mutation_detector import MutationDetector
from kube_batch_tpu.cache.store import EventHandler
from kube_batch_tpu.recovery import (
    CycleBudget,
    CycleDeadlineExceeded,
    WriteIntentJournal,
    reconcile_journal,
)
from kube_batch_tpu.recovery.fsck import fsck, main as fsck_main
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.server import SchedulerServer, WatchHub
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


XLA_CONF = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def seed_store(store: ClusterStore, gangs: int = 2, members: int = 6) -> None:
    """gangs x members pending gang pods on 4 nodes."""
    store.create_queue(build_queue("default"))
    for i in range(4):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=32))
        )
    for g in range(gangs):
        store.create_pod_group(build_pod_group(f"g{g}", min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"g{g}-p{m}", group_name=f"g{g}",
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def make_scheduler(store, tmp_path, journal=None, binder=None):
    conf = tmp_path / "conf.yaml"
    conf.write_text(XLA_CONF)
    cache = SchedulerCache(store, journal=journal, binder=binder)
    return cache, Scheduler(cache, scheduler_conf=str(conf), schedule_period=0.05)


def placements(store) -> dict:
    return {f"{p.namespace}/{p.name}": p.node_name for p in store.list("pods")}


# -- journal unit ------------------------------------------------------------


def test_journal_append_confirm_outstanding_roundtrip(tmp_path):
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    seqs = j.append_intents(
        "bind", [("g", "default/a", "n0"), ("g", "default/b", "n1")], cycle=7
    )
    j.append_intents("evict", [("h", "default/c", "")], cycle=7)
    j.confirm(seqs[0])
    j.confirm(seqs[0])  # idempotent
    out = j.outstanding()
    assert [(i.op, i.pod) for i in out] == [
        ("bind", "default/b"), ("evict", "default/c"),
    ]
    assert all(i.cycle == 7 for i in out)
    # a fresh handle on the same file sees the same truth (crash replay)
    replay = WriteIntentJournal.replay(path)
    assert len(replay.intents) == 3 and len(replay.confirmed) == 1
    assert [i.pod for i in replay.orphans] == ["default/b", "default/c"]
    j.close()


def test_journal_survives_torn_tail_and_compacts(tmp_path):
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    seqs = j.append_intents("bind", [("g", "default/a", "n0"), ("g", "default/b", "n0")])
    j.confirm(seqs[0])
    j.close()
    with open(path, "a") as fh:
        fh.write('{"rec":"intent","seq":99,"cyc')  # crash mid-append
    replay = WriteIntentJournal.replay(path)
    assert replay.corrupt == 1
    assert [i.seq for i in replay.orphans] == [seqs[1]]
    # reopening resumes seq numbering past everything seen and compaction
    # drops confirmed history + the torn tail
    j2 = WriteIntentJournal(path)
    j2.compact()
    replay2 = WriteIntentJournal.replay(path)
    assert replay2.corrupt == 0
    assert set(replay2.intents) == {seqs[1]}
    new = j2.append_intents("bind", [("g", "default/c", "n1")])
    assert new[0] > seqs[1]
    j2.close()


def test_fsck_reports_orphans_and_strict_gates(tmp_path, capsys):
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    seqs = j.append_intents(
        "bind", [("default/g0", "default/p0", "n0"), ("default/g0", "default/p1", "n1")]
    )
    j.confirm(seqs[0])
    j.close()
    summary = fsck(path)
    assert summary["intents"] == 2 and summary["confirmed"] == 1
    assert summary["orphaned"] == 1
    assert summary["orphaned_gangs"] == {"cycle=0 gang=default/g0": 1}
    # CLI: rc 0 with orphans (normal after a crash), rc 1 under --strict
    assert fsck_main([path]) == 0
    assert fsck_main(["--strict", path]) == 1
    assert fsck_main(["--json", path]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["orphaned"] == 1
    assert fsck_main([str(tmp_path / "missing.wal")]) == 0  # empty journal is clean


# -- reconciliation ----------------------------------------------------------


def test_reconcile_confirms_landed_redispatches_orphans(tmp_path):
    store = ClusterStore()
    seed_store(store, gangs=1, members=3)
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    j.append_intents(
        "bind",
        [
            ("default/g0", "default/g0-p0", "n0"),  # will land
            ("default/g0", "default/g0-p1", "n1"),  # orphaned
            ("default/g0", "default/g0-p2", "n2"),  # orphaned
        ],
        cycle=1,
    )
    # the dead leader's write pool completed only the first write
    import dataclasses

    p0 = store.get_pod("default", "g0-p0")
    store.update_pod(dataclasses.replace(p0, node_name="n0"))

    det = MutationDetector(store)
    det.snapshot()
    report = reconcile_journal(j, store)
    assert det.violations() == []  # reconciliation replaces, never mutates
    assert report.confirmed == 1 and report.redispatched == 2
    assert report.rolled_back == 0 and not report.aborted
    assert placements(store) == {
        "default/g0-p0": "n0", "default/g0-p1": "n1", "default/g0-p2": "n2",
    }
    # journal is clean afterwards: nothing for the next takeover
    assert j.outstanding() == []
    assert fsck(path)["orphaned"] == 0
    j.close()


def test_reconcile_rolls_back_half_bound_gang_when_member_unfixable(tmp_path):
    """Gang atomicity: a member pod vanished while the leader was down —
    the gang cannot reach min_member, so its landed and re-dispatched
    binds are rolled back (statement-style reverse undo)."""
    store = ClusterStore()
    seed_store(store, gangs=1, members=3)
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    j.append_intents(
        "bind",
        [
            ("default/g0", "default/g0-p0", "n0"),  # landed before the crash
            ("default/g0", "default/g0-p1", "n1"),  # orphaned, fixable
            ("default/g0", "default/g0-p2", "n2"),  # orphaned, pod deleted
        ],
        cycle=1,
    )
    import dataclasses

    p0 = store.get_pod("default", "g0-p0")
    store.update_pod(dataclasses.replace(p0, node_name="n0"))
    store.delete_pod("default", "g0-p2")

    report = reconcile_journal(j, store)
    assert report.gangs_rolled_back == ["default/g0"]
    assert report.rolled_back >= 1
    # every surviving member is back to Pending/unbound: the gang will
    # be rescheduled whole (or not at all) by the next leader's cycle
    assert placements(store) == {"default/g0-p0": "", "default/g0-p1": ""}
    assert j.outstanding() == []
    j.close()


def test_reconcile_respects_store_truth_on_conflict(tmp_path):
    """A pod bound elsewhere while the leader was down is left alone —
    store truth wins (the Omega conflict rule)."""
    store = ClusterStore()
    seed_store(store, gangs=1, members=2)
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    j.append_intents(
        "bind",
        [
            ("default/g0", "default/g0-p0", "n0"),
            ("default/g0", "default/g0-p1", "n1"),
        ],
    )
    import dataclasses

    p0 = store.get_pod("default", "g0-p0")
    store.update_pod(dataclasses.replace(p0, node_name="n3"))  # rival bound it

    report = reconcile_journal(j, store)
    assert report.conflicts == 1 and report.redispatched == 1
    assert placements(store) == {"default/g0-p0": "n3", "default/g0-p1": "n1"}
    j.close()


def test_reconcile_degrades_on_journal_replay_and_scan_faults(tmp_path):
    store = ClusterStore()
    seed_store(store, gangs=1, members=2)
    path = str(tmp_path / "j.wal")
    j = WriteIntentJournal(path)
    j.append_intents("bind", [("default/g0", "default/g0-p0", "n0")])
    before = placements(store)

    faults.registry.arm("journal.replay", count=1)
    report = reconcile_journal(j, store)
    assert report.aborted and placements(store) == before

    faults.registry.reset()
    faults.registry.arm("reconcile.scan", count=1)
    report = reconcile_journal(j, store)
    assert report.aborted and placements(store) == before

    # fault cleared: the next takeover completes the work
    faults.registry.reset()
    report = reconcile_journal(j, store)
    assert report.redispatched == 1
    assert placements(store)["default/g0-p0"] == "n0"
    j.close()


# -- the chaos e2e: leader dies mid-bulk-bind --------------------------------


class _LeaderKilled(BaseException):
    """SIGKILL stand-in: BaseException so neither the write-retry ladder
    nor the resync routing (both catch Exception) can 'survive' it —
    the write pool dies exactly where a killed process would."""


class DyingBinder(StoreBinder):
    """Store binder that dies after N successful writes (mid-batch)."""

    def __init__(self, store, die_after: int) -> None:
        super().__init__(store)
        self.left = die_after

    def bind(self, pod, hostname: str) -> None:
        if self.left <= 0:
            raise _LeaderKilled()
        self.left -= 1
        super().bind(pod, hostname)


def _count_bind_events(store) -> dict:
    """pod key -> number of unbound->bound transitions (duplicate-bind
    detector for the acceptance criterion)."""
    counts: dict[str, int] = {}

    def on_update(old, new):
        if not old.node_name and new.node_name:
            key = f"{new.namespace}/{new.name}"
            counts[key] = counts.get(key, 0) + 1

    store.add_event_handler("pods", EventHandler(on_update=on_update))
    return counts


def test_chaos_leader_killed_mid_bulk_bind_standby_reconciles(tmp_path):
    """THE acceptance e2e: SIGKILL the leader mid-`bind_many` (after the
    journal appended the whole statement, after some store writes
    landed); the standby reconciles on takeover; final placements are
    bind-for-bind equal to an uninterrupted run — zero lost, zero
    duplicate binds; mutation detector armed (conftest) for the leader
    cycle and explicitly around reconciliation."""
    # uninterrupted twin: same seed, run to completion
    twin = ClusterStore()
    seed_store(twin)
    _, sched_t = make_scheduler(twin, tmp_path)
    sched_t.run_once()
    expected = placements(twin)
    assert all(expected.values()) and len(expected) == 12

    # the real run: leader journaled, killed after 4 of 12 bulk writes
    store = ClusterStore()
    seed_store(store)
    bind_counts = _count_bind_events(store)
    journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    _, sched = make_scheduler(
        store, tmp_path, journal=journal, binder=DyingBinder(store, die_after=4)
    )
    with pytest.raises(_LeaderKilled):
        sched.run_once()
    landed = {k: v for k, v in placements(store).items() if v}
    assert 0 < len(landed) < 12, "kill must land mid-batch"
    orphans = WriteIntentJournal.replay(journal.path).orphans
    assert len(orphans) == 12 - len(landed), "journal must hold the in-flight suffix"

    # standby takeover: fresh process (new journal handle, fresh cache
    # built from store truth), reconcile before its loop runs
    standby_journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    det = MutationDetector(store)
    det.snapshot()
    report = reconcile_journal(standby_journal, store)
    assert det.violations() == []
    assert report.redispatched == 12 - len(landed)
    assert report.rolled_back == 0

    final = placements(store)
    assert final == expected, "reconciled placements must equal the uninterrupted run"
    assert all(n == 1 for n in bind_counts.values()), f"duplicate binds: {bind_counts}"
    assert set(bind_counts) == set(expected), "lost binds"

    # the standby's own scheduling loop finds a fully-bound world: a
    # second cycle must not move or re-bind anything
    cache_b, sched_b = make_scheduler(store, tmp_path)
    sched_b.run_once()
    assert placements(store) == expected
    assert all(n == 1 for n in bind_counts.values())
    standby_journal.close()


def test_server_start_reconciles_seeded_journal(tmp_path):
    """The server-level wiring: a SchedulerServer handed a journal with
    orphaned intents (the dead leader's) re-drives them at start(),
    before its loop schedules anything."""
    store = ClusterStore()
    seed_store(store, gangs=1, members=4)
    path = str(tmp_path / "leader.wal")
    j = WriteIntentJournal(path)
    j.append_intents(
        "bind",
        [("default/g0", f"default/g0-p{m}", f"n{m % 4}") for m in range(4)],
        cycle=9,
    )
    j.close()
    srv = SchedulerServer(
        listen_address="127.0.0.1:0", schedule_period=0.05,
        store=store, journal_path=path,
    )
    srv.start()
    try:
        wait_until(
            lambda: all(p.node_name for p in store.list("pods")),
            what="journal orphans re-dispatched at takeover",
        )
        assert placements(store) == {
            f"default/g0-p{m}": f"n{m % 4}" for m in range(4)
        }
    finally:
        srv.stop()


def test_journal_append_fault_degrades_to_unjournaled_dispatch(tmp_path):
    """journal.append: a WAL I/O failure must not brick the write side —
    the batch dispatches unjournaled, loudly metered, and binds land."""
    store = ClusterStore()
    seed_store(store, gangs=1, members=4)
    journal = WriteIntentJournal(str(tmp_path / "j.wal"))
    _, sched = make_scheduler(store, tmp_path, journal=journal)
    before = metrics.journal_records.value({"state": "append_failed"})
    faults.registry.arm("journal.append")
    sched.run_once()
    assert all(placements(store).values()), "binds lost under journal failure"
    assert metrics.journal_records.value({"state": "append_failed"}) > before
    assert journal.outstanding() == []  # nothing journaled, nothing orphaned
    journal.close()


# -- cycle deadline budget ---------------------------------------------------


def test_budget_soft_and_hard_semantics():
    now = [0.0]
    b = CycleBudget(soft_s=1.0, hard_s=2.0, clock=lambda: now[0])
    assert not b.soft_exceeded() and not b.hard_exceeded()
    assert b.remaining() == 2.0
    now[0] = 1.5
    assert b.soft_exceeded() and not b.hard_exceeded()
    now[0] = 2.5
    assert b.hard_exceeded()
    with pytest.raises(CycleDeadlineExceeded, match="dispatch"):
        b.check("dispatch barrier")
    # no deadlines configured: never exceeded, infinite budget
    b2 = CycleBudget()
    assert b2.remaining() == float("inf") and not b2.hard_exceeded()


def test_hard_deadline_abort_leaves_cache_byte_identical_then_reschedules(tmp_path):
    """Satellite regression: the cycle.overrun drill fires at the
    dispatch barrier (after encode+solve+replay) — the abort discards
    the session wholesale, the store is BYTE-identical (same objects,
    mutation detector armed via conftest), and the next cycle
    reschedules the aborted gangs."""
    store = ClusterStore()
    seed_store(store)
    before_objs = {f"{p.namespace}/{p.name}": p for p in store.list("pods")}
    before_pgs = list(store.list("podgroups"))
    _, sched = make_scheduler(store, tmp_path)

    h_before = metrics.cycle_overruns.value({"kind": "hard"})
    faults.registry.arm("cycle.overrun", count=1)
    sched.run_once()  # aborts pre-dispatch; detector verifies inside
    assert metrics.cycle_overruns.value({"kind": "hard"}) == h_before + 1
    after_objs = {f"{p.namespace}/{p.name}": p for p in store.list("pods")}
    assert set(after_objs) == set(before_objs)
    for key, pod in after_objs.items():
        assert pod is before_objs[key], f"{key} was written during an aborted cycle"
    for pg_before, pg_after in zip(before_pgs, store.list("podgroups")):
        assert pg_after is pg_before, "podgroup status written during an aborted cycle"

    # fault consumed (count=1): the next cycle binds everything
    sched.run_once()
    final = placements(store)
    assert all(final.values()) and len(final) == 12


def test_soft_overrun_arms_ladder_downgrade(tmp_path, monkeypatch):
    """A cycle past its soft deadline records a failure against the tier
    that ran it; at the breaker threshold the ladder downgrades."""
    monkeypatch.setenv("KBT_CYCLE_SOFT_DEADLINE_S", "0.000001")
    from kube_batch_tpu.faults.ladder import OPEN, DegradationLadder

    ladder = DegradationLadder(
        ("mesh_pallas", "pallas", "xla", "serial"),
        failure_threshold=2, reset_timeout=30.0,
    )
    monkeypatch.setattr(faults, "solver_ladder", ladder)
    store = ClusterStore()
    seed_store(store, gangs=1, members=2)
    _, sched = make_scheduler(store, tmp_path)
    s_before = metrics.cycle_overruns.value({"kind": "soft"})
    sched.run_once()  # any real cycle exceeds a 1us soft deadline
    assert metrics.cycle_overruns.value({"kind": "soft"}) == s_before + 1
    assert ladder.state("xla") == "closed"  # one overrun: streak armed only
    # drain + re-pend: second slow cycle trips the threshold
    for p in store.list("pods"):
        store.delete_pod(p.namespace, p.name)
    for m in range(2):
        store.create_pod(
            build_pod(
                name=f"r-p{m}", group_name="g0",
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
        )
    sched.run_once()
    assert ladder.state("xla") == OPEN, "repeated soft overruns must arm the downgrade"


# -- bounded staleness -------------------------------------------------------


def test_staleness_guard_refuses_to_schedule(tmp_path, monkeypatch):
    monkeypatch.setenv("KBT_MAX_SNAPSHOT_AGE_S", "5")
    store = ClusterStore()
    seed_store(store, gangs=1, members=2)
    age = [999.0]
    cache = SchedulerCache(store, staleness_fn=lambda: age[0])
    conf = tmp_path / "conf.yaml"
    conf.write_text(XLA_CONF)
    sched = Scheduler(cache, scheduler_conf=str(conf), schedule_period=0.05)
    before = metrics.stale_cycles_skipped.value()
    sched.run_once()
    assert metrics.stale_cycles_skipped.value() == before + 1
    assert not any(placements(store).values()), "scheduled over a stale snapshot"
    age[0] = 0.0  # watch caught up
    sched.run_once()
    assert all(placements(store).values())


def test_watchhub_per_kind_ring_overflow_gone_and_isolation():
    """Satellite: the per-kind ring bounds a slow watcher's buffer with
    true 410 on overflow, churn in one kind cannot evict another kind's
    events, and the documented contract (re-list, resume) converges."""
    store = ClusterStore()
    hub = WatchHub(store, max_events=8)
    import threading

    stop = threading.Event()
    rv0 = hub.resource_version
    store.create_node(build_node("n-keep", build_resource_list(cpu=1)))
    # churn queues far past the ring capacity
    for i in range(32):
        store.create_queue(build_queue(f"q{i}"))
        store.delete_queue(f"q{i}")
    # the queue watcher fell out of its ring: true 410
    status, _, rv = hub.poll("queues", rv0, 0, stop)
    assert status == "gone"
    # the node watcher is untouched by queue churn: its event survives
    status, events, _ = hub.poll("nodes", rv0, 0, stop)
    assert status == "ok"
    assert [e["object"]["name"] for e in events] == ["n-keep"]
    # the contract: re-list, resume from the fresh rv, convergence
    listed = {q.name for q in store.list("queues")}
    rv = hub.resource_version
    assert listed == set()
    store.create_queue(build_queue("after-relist"))
    status, events, rv = hub.poll("queues", rv, 0, stop)
    assert status == "ok"
    assert [e["object"]["name"] for e in events] == ["after-relist"]


def test_resilient_watcher_converges_and_reports_age():
    """ResilientWatcher against a live server: initial list + watch
    convergence, snapshot age ~0 while healthy, inf before first sync."""
    from kube_batch_tpu.recovery import ResilientWatcher

    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    w = ResilientWatcher(
        f"http://127.0.0.1:{srv.listen_port}", ("queues",),
        poll_timeout=0.5, min_backoff=0.01, relist_min_interval=0.05,
    )
    try:
        assert w.snapshot_age() == float("inf")
        w.start()
        srv.store.create_queue(build_queue("tenant-a", weight=3))
        wait_until(
            lambda: set(w.mirror["queues"]) == {"default", "tenant-a"},
            what="watcher mirror convergence",
        )
        assert w.snapshot_age() < 5.0
        assert not w.stale(5.0)
        srv.store.delete_queue("tenant-a")
        wait_until(
            lambda: set(w.mirror["queues"]) == {"default"},
            what="delete propagates to the mirror",
        )
    finally:
        w.stop()
        srv.stop()


def test_relist_coalescing_damps_a_gone_storm():
    """Back-to-back relists are coalesced to one per interval: the
    second call waits out the window (storm damper, not a tight loop)."""
    from kube_batch_tpu.recovery import ResilientWatcher

    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    w = ResilientWatcher(
        f"http://127.0.0.1:{srv.listen_port}", ("queues",),
        poll_timeout=0.5, relist_min_interval=0.25,
    )
    try:
        t0 = time.monotonic()
        w.list_kind("queues")
        w.list_kind("queues")  # inside the window: waits it out
        assert time.monotonic() - t0 >= 0.25
    finally:
        w.stop()
        srv.stop()


# -- errTasks terminal drop (satellite) --------------------------------------


def test_resync_queue_terminal_drop_after_retry_budget(monkeypatch):
    """A permanently-unsyncable task is dropped from errTasks after its
    retry budget, metered and narrated — it cannot ride the queue
    forever."""
    monkeypatch.setenv("KBT_RESYNC_MAX_RETRIES", "3")
    store = ClusterStore()
    store.create_queue(build_queue("default"))
    cache = SchedulerCache(store)
    from kube_batch_tpu.testing import build_task

    ghost = build_task(name="ghost", group_name="nojob")
    ghost.job = "default/nojob"  # no such job, no such pod: sync always fails
    before = metrics.resync_dropped.value()
    cache.resync_task(ghost)
    deadline = time.monotonic() + 10
    while len(cache._err_tasks) > 0 and time.monotonic() < deadline:
        cache._process_resync_task()
    assert len(cache._err_tasks) == 0, "task still riding the queue"
    assert metrics.resync_dropped.value() == before + 1
    # the failure count was forgotten with the drop: a LATER event for
    # the same pod starts a fresh budget
    assert cache._err_tasks.failures(ghost) == 0
