"""Tier-1 tests for the trace-level program auditor
(kube_batch_tpu.analysis.trace) and the compile-cache sentinel.

Each KBT-P code is proven on a seeded fixture — a tiny program carrying
exactly the defect the check exists to catch — plus its negative twin
(the compliant spelling must NOT fire). The sentinel is proven against
a deliberate recompile storm (shape-keyed jit churn) and against the
warm loop it must certify. The acceptance-critical budget — zero
recompiles across three consecutive warm cycles — is pinned here for
the XLA twin and the GSPMD sharded rung on the real solver programs.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_batch_tpu.analysis import apply_baseline, load_baseline  # noqa: E402
from kube_batch_tpu.analysis import trace  # noqa: E402
from kube_batch_tpu.analysis.trace import (  # noqa: E402
    build_snapshot,
    check_callbacks,
    check_donation,
    check_f64,
    check_large_consts,
    check_signature_drift,
)
from kube_batch_tpu.analysis.trace.sentinel import (  # noqa: E402
    CompileBudgetExceeded,
    CompileSentinel,
)
from kube_batch_tpu.testing import x64_enabled  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(findings) -> list[str]:
    return [f.code for f in findings]


@pytest.fixture(scope="module")
def snapshot():
    return build_snapshot()


# -- KBT-P001: host callbacks ------------------------------------------------


def test_p001_pure_callback_fires():
    def host_hop(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    closed = jax.make_jaxpr(host_hop)(jnp.ones((4,), jnp.float32))
    findings = check_callbacks(closed, "fix", "kube_batch_tpu/ops/fix.py")
    assert codes(findings) == ["KBT-P001"]
    assert findings[0].symbol == "fix.callback.pure_callback"


def test_p001_pure_device_program_clean():
    closed = jax.make_jaxpr(lambda x: jnp.sin(x) * 2)(jnp.ones((4,), jnp.float32))
    assert check_callbacks(closed, "fix", "p") == []


def test_p001_callback_found_through_jit_nesting():
    @jax.jit
    def inner(x):
        jax.debug.print("x={x}", x=x)
        return x

    closed = jax.make_jaxpr(lambda x: inner(x) + 1)(jnp.ones((4,), jnp.float32))
    findings = check_callbacks(closed, "fix", "p")
    assert codes(findings) == ["KBT-P001"]


# -- KBT-P002: f64 upcast with f32 inputs ------------------------------------


def test_p002_default_dtype_where_leaks_f64_under_x64():
    # the exact leak pattern scrubbed out of the live kernels: a
    # two-python-scalar where takes the x64 default dtype
    def leak(x):
        return jnp.where(x == 0, 0.0, 1.0)

    with x64_enabled():
        closed = jax.make_jaxpr(leak)(jax.ShapeDtypeStruct((4,), np.float32))
    findings = check_f64(closed, "fix", "kube_batch_tpu/ops/fix.py")
    assert codes(findings) == ["KBT-P002"]
    assert findings[0].symbol == "fix.f64"


def test_p002_dtype_pinned_twin_clean():
    def pinned(x):
        return jnp.where(x == 0, (x != 0).astype(x.dtype), x)

    with x64_enabled():
        closed = jax.make_jaxpr(pinned)(jax.ShapeDtypeStruct((4,), np.float32))
    assert check_f64(closed, "fix", "p") == []


def test_p002_deliberate_f64_inputs_exempt():
    with x64_enabled():
        closed = jax.make_jaxpr(lambda x: x * 0.5)(
            jax.ShapeDtypeStruct((4,), np.float64)
        )
    assert check_f64(closed, "fix", "p") == []


# -- KBT-P003: large captured host constants ---------------------------------


def test_p003_large_captured_constant_fires():
    table = np.zeros((300_000,), np.float32)  # 1.14 MiB > the 1 MiB default

    closed = jax.make_jaxpr(lambda x: (x + table).sum())(jnp.float32(0))
    findings = check_large_consts(closed, "fix", "kube_batch_tpu/ops/fix.py")
    assert codes(findings) == ["KBT-P003"]
    assert findings[0].symbol == "fix.const.300000"
    assert "KiB" in findings[0].message


def test_p003_small_constant_clean():
    small = np.zeros((8,), np.float32)
    closed = jax.make_jaxpr(lambda x: (x + small).sum())(jnp.float32(0))
    assert check_large_consts(closed, "fix", "p") == []


def test_p003_threshold_is_configurable():
    table = np.zeros((1024,), np.float32)
    closed = jax.make_jaxpr(lambda x: (x + table).sum())(jnp.float32(0))
    assert codes(check_large_consts(closed, "fix", "p", const_bytes=1024)) == [
        "KBT-P003"
    ]


# -- KBT-P004: donation declared but not honored -----------------------------


def test_p004_unhonorable_donation_fires():
    # donating the input of a reduction: no output shares its layout, so
    # XLA cannot alias and jax warns
    bad = jax.jit(lambda b: b.sum(), donate_argnums=(0,))
    buf = jax.ShapeDtypeStruct((128, 2), np.float32)
    findings = check_donation(bad, (buf,), "fix", "kube_batch_tpu/ops/fix.py")
    assert codes(findings) == ["KBT-P004"]
    assert findings[0].symbol == "fix.donation"


def test_p004_honored_scatter_donation_clean():
    # the arena row-scatter shape: output aliases the donated buffer
    good = jax.jit(lambda b, i, v: b.at[i].set(v), donate_argnums=(0,))
    buf = jax.ShapeDtypeStruct((128, 2), np.float32)
    idx = jax.ShapeDtypeStruct((4,), np.int32)
    vals = jax.ShapeDtypeStruct((4, 2), np.float32)
    assert check_donation(good, (buf, idx, vals), "fix", "p") == []


# -- KBT-P005: cross-tier signature drift ------------------------------------


def test_p005_signature_drift_fires_per_field():
    ref = {"it": ((), "int32"), "idle": ((128, 2), "float32")}
    other = {"it": ((), "int64"), "idle": ((128, 2), "float32")}
    findings = check_signature_drift(ref, other, "xla_twin", "mesh@2", "p")
    assert codes(findings) == ["KBT-P005"]
    assert findings[0].symbol == "mesh@2.drift.it"


def test_p005_missing_field_counts_as_drift_both_ways():
    ref = {"it": ((), "int32")}
    assert codes(check_signature_drift(ref, {}, "a", "b", "p")) == ["KBT-P005"]
    assert codes(check_signature_drift({}, ref, "a", "b", "p")) == ["KBT-P005"]


def test_p005_identical_signatures_clean():
    ref = {"it": ((), "int32"), "idle": ((128, 2), "float32")}
    assert check_signature_drift(ref, dict(ref), "a", "b", "p") == []


# -- compile sentinel --------------------------------------------------------


def test_sentinel_counts_a_seeded_recompile_storm():
    f = jax.jit(lambda x: x * 2 + 1)
    xs = [jnp.ones((n,), jnp.float32) for n in (3, 5, 7, 9)]
    with CompileSentinel("storm") as cs:
        for x in xs:
            jax.block_until_ready(f(x))
    # every distinct shape is a fresh backend compile
    assert cs.compiles >= len(xs)


def test_sentinel_budget_zero_raises_on_churn():
    f = jax.jit(lambda x: x - 3)
    xs = [jnp.ones((n,), jnp.float32) for n in (11, 13)]
    with pytest.raises(CompileBudgetExceeded, match="retracing"):
        with CompileSentinel("storm", budget=0):
            for x in xs:
                jax.block_until_ready(f(x))


def test_sentinel_warm_loop_is_free():
    f = jax.jit(lambda x: x + 1)
    x = jnp.ones((16,), jnp.float32)
    jax.block_until_ready(f(x))  # compile outside the region
    with CompileSentinel("warm", budget=0) as cs:
        for _ in range(3):
            jax.block_until_ready(f(x))
    assert cs.compiles == 0


def test_sentinel_never_masks_an_exception_in_flight():
    f = jax.jit(lambda x: x * 5)
    x = jnp.ones((17,), jnp.float32)
    with pytest.raises(ValueError, match="boom"):
        with CompileSentinel("mask", budget=0):
            jax.block_until_ready(f(x))  # blows the budget...
            raise ValueError("boom")  # ...but the real error wins


# -- acceptance: zero recompiles across 3 warm cycles ------------------------


def test_xla_twin_three_warm_cycles_zero_recompiles(snapshot):
    from kube_batch_tpu.ops.kernels import _solve_fresh

    dev = jax.device_put(snapshot)
    jax.block_until_ready(_solve_fresh(dev, True, True))  # compile + warm
    with CompileSentinel("xla_twin warm cycles", budget=0) as cs:
        for _ in range(3):
            jax.block_until_ready(_solve_fresh(dev, True, True))
    assert cs.compiles == 0


def test_sharded_rung_three_warm_cycles_zero_recompiles(snapshot):
    from kube_batch_tpu.parallel.sharded import AXIS_NAME, _sharded_programs

    devices = tuple(jax.devices())
    if len(devices) < 2:
        pytest.skip("needs >=2 host devices (conftest forces 8)")
    fresh, _resume = _sharded_programs(
        devices[:2], AXIS_NAME, frozenset(snapshot), True, True
    )
    jax.block_until_ready(fresh(snapshot))  # compile + warm
    with CompileSentinel("sharded@2 warm cycles", budget=0) as cs:
        for _ in range(3):
            jax.block_until_ready(fresh(snapshot))
    assert cs.compiles == 0


# -- live tree ---------------------------------------------------------------


def test_snapshot_speaks_the_action_layer_contract(snapshot):
    # host-only metadata dropped, nodeorder weights folded in, all f32 —
    # the exact dict actions/xla_allocate hands the solvers
    assert "task_created" not in snapshot
    for k in ("w_least", "w_balanced", "w_aff", "w_podaff"):
        assert snapshot[k].dtype == np.float32
    # node bucket pads so every mesh size in {1,2,4,8} divides it
    n_nodes = snapshot["node_idle"].shape[0]
    assert all(n_nodes % m == 0 for m in trace.MESH_SIZES_DEFAULT)


def test_live_tree_trace_audit_clean_under_committed_baseline():
    findings, info = trace.run_trace_audit()
    bl = load_baseline(os.path.join(REPO, "hack", "trace-baseline.toml"), REPO)
    assert bl.errors == [], [e.message for e in bl.errors]
    kept, _suppressed, _stale = apply_baseline(findings, bl)
    assert kept == [], "unsuppressed trace findings:\n" + "\n".join(
        f.render() for f in kept
    )
    # every tier was actually traced
    assert info["entries"]["xla_twin"] > 0
    assert info["entries"]["pallas_solve"] > 0
    assert any(e.startswith("mesh_pallas@") for e in info["entries"])
