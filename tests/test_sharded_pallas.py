"""Blocked sharded-Pallas solver ≡ single-chip solve ≡ serial.

The blocked path (parallel/sharded_pallas.ShardedPallasSolver) runs the
fused block kernel per shard with one argmax exchange per gang
iteration; these tests pin it, decision for decision, against the XLA
while-loop twin (itself pinned against the serial oracle in
test_xla_allocate) at mesh sizes {1, 2, 4, 8} on the virtual CPU mesh,
and bind-for-bind against the serial action through the real
xla_allocate routing — including the segmented pod-affinity
pause/resume hybrid and the per-shard VMEM envelope gate.
"""

import os

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401  (registers actions)
import kube_batch_tpu.plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu import faults
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.models import multi_queue, synthetic
from kube_batch_tpu.ops import pallas_solve
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.ops.kernels import solve_allocate_state
from kube_batch_tpu.parallel import make_mesh
from kube_batch_tpu.parallel.sharded_pallas import ShardedPallasSolver
from kube_batch_tpu.testing import FakeCache

DEFAULT_TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def f32_arrays(cluster, drf=True, proportion=True):
    ssn = open_session(
        FakeCache(cluster), parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers
    )
    enc = encode_session(
        ssn.jobs,
        ssn.nodes,
        ssn.queues,
        dtype=np.float32,
        drf=ssn.plugins.get("drf") if drf else None,
        proportion=ssn.plugins.get("proportion") if proportion else None,
    )
    close_session(ssn)
    a = dict(enc.arrays)
    for k in ("w_least", "w_balanced", "w_aff", "w_podaff"):
        a[k] = np.float32(1)
    return a


def assert_assignment_equal(ref, got, ctx=""):
    np.testing.assert_array_equal(
        np.asarray(ref.assigned_node), np.asarray(got.assigned_node),
        err_msg=f"{ctx}: node",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.assigned_kind), np.asarray(got.assigned_kind),
        err_msg=f"{ctx}: kind",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.assign_pos), np.asarray(got.assign_pos),
        err_msg=f"{ctx}: pos",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.ready_cnt), np.asarray(got.ready_cnt),
        err_msg=f"{ctx}: ready",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.job_active), np.asarray(got.job_active),
        err_msg=f"{ctx}: active",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.q_dropped), np.asarray(got.q_dropped),
        err_msg=f"{ctx}: q_dropped",
    )
    assert int(ref.step) == int(got.step), f"{ctx}: step"
    np.testing.assert_allclose(
        np.asarray(ref.idle), np.asarray(got.idle), err_msg=f"{ctx}: idle"
    )
    np.testing.assert_allclose(
        np.asarray(ref.used), np.asarray(got.used), err_msg=f"{ctx}: used"
    )


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_blocked_sharded_matches_xla_twin(n_devices):
    """The same f32 snapshot through the XLA while-loop twin and the
    blocked sharded solver (jnp block backend on the CPU mesh) must
    agree on every assignment and on the final node state."""
    a = f32_arrays(synthetic(120, 24, seed=3))
    ref = solve_allocate_state(a, None, enable_drf=True, enable_proportion=True)
    got = ShardedPallasSolver(
        a, make_mesh(n_devices), enable_drf=True, enable_proportion=True
    ).solve(None)
    assert_assignment_equal(ref, got, ctx=f"mesh {n_devices}")


@pytest.mark.parametrize("n_devices", [2, 8])
def test_blocked_sharded_multi_queue(n_devices):
    a = f32_arrays(multi_queue(96, 16, n_queues=3, tasks_per_job=6, seed=7))
    ref = solve_allocate_state(a, None, enable_drf=True, enable_proportion=True)
    got = ShardedPallasSolver(
        a, make_mesh(n_devices), enable_drf=True, enable_proportion=True
    ).solve(None)
    assert_assignment_equal(ref, got, ctx=f"mesh {n_devices}")


@pytest.mark.parametrize("n_devices", [2, 4])
def test_blocked_interpret_kernel_matches(n_devices):
    """The actual Pallas block kernel through the interpreter — the code
    the TPU mesh compiles with Mosaic — against the XLA twin."""
    a = f32_arrays(synthetic(80, 16, seed=5))
    ref = solve_allocate_state(a, None, enable_drf=True, enable_proportion=True)
    got = ShardedPallasSolver(
        a, make_mesh(n_devices), enable_drf=True, enable_proportion=True,
        block_impl="interpret",
    ).solve(None)
    assert_assignment_equal(ref, got, ctx=f"interpret mesh {n_devices}")


# -- through the real action: routing, serial parity, pause/resume -------


def run_action(cluster_fn, mesh_spec, env=None):
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        cache = FakeCache(cluster_fn())
        ssn = open_session(
            cache,
            parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers,
            {"xla_allocate": {"mesh": mesh_spec}},
        )
        action = XlaAllocateAction(dtype=np.float32)
        action.execute(ssn)
        close_session(ssn)
        return (
            dict(cache.binder.binds),
            action.last_solver_tier,
            action.last_mesh_size,
        )
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_serial(cluster_fn):
    from kube_batch_tpu.framework import get_action

    cache = FakeCache(cluster_fn())
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    get_action("allocate").execute(ssn)
    close_session(ssn)
    return dict(cache.binder.binds)


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_action_mesh_pallas_binds_match_serial(n_devices):
    """Bind-for-bind identity with the serial path through the real
    action at every mesh size; sizes > 1 must actually take the
    mesh_pallas rung (loud failure, never a silent downgrade)."""
    def mk():
        return multi_queue(600, 64, n_queues=3, tasks_per_job=6, seed=11)

    spec = f"cpu:{n_devices}" if n_devices > 1 else "off"
    binds, tier, mesh_n = run_action(mk, spec)
    if n_devices > 1:
        assert mesh_n == n_devices
        assert tier == "mesh_pallas", f"expected mesh_pallas rung, got {tier}"
    serial = run_serial(mk)
    assert binds == serial and len(binds) == 600


def _pod_affinity_cluster():
    from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    anchor = build_pod(
        name="anchor",
        node_name="n0",
        phase=PodPhase.RUNNING,
        req=build_resource_list(cpu=1, memory="128Mi"),
        labels={"app": "db"},
    )
    pods, groups = [anchor], []
    for i in range(12):
        p = build_pod(
            name=f"p{i}",
            group_name=f"g{i}",
            req=build_resource_list(cpu=1, memory="256Mi"),
        )
        p.metadata.creation_timestamp = float(i)
        if i in (4, 9):  # two host-only tasks -> two pause/resume trips
            p.affinity = Affinity(
                pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
            )
        pg = build_pod_group(f"g{i}", min_member=1)
        pg.metadata.creation_timestamp = float(i)
        pods.append(p)
        groups.append(pg)
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
        for i in range(4)
    ]
    return build_cluster(pods, nodes, groups, [build_queue("default")])


@pytest.mark.parametrize("n_devices", [2, 4])
def test_action_mesh_pallas_pause_resume_parity(n_devices):
    """The segmented pod-affinity hybrid on the mesh_pallas rung: the
    paused state is gathered to host, serial-stepped, and re-enters the
    blocked sharded resume program — binds must match the serial path
    and the single-chip run."""
    binds, tier, mesh_n = run_action(_pod_affinity_cluster, f"cpu:{n_devices}")
    assert mesh_n == n_devices
    assert tier == "mesh_pallas"
    single, _, _ = run_action(_pod_affinity_cluster, "off")
    serial = run_serial(_pod_affinity_cluster)
    assert binds == single == serial and len(binds) == 12


# -- the per-shard VMEM envelope ------------------------------------------


def test_block_vmem_scales_with_mesh():
    a = f32_arrays(multi_queue(600, 640, n_queues=3, tasks_per_job=6, seed=2))
    b1 = pallas_solve.block_vmem_bytes(a, 1)
    b4 = pallas_solve.block_vmem_bytes(a, 4)
    b8 = pallas_solve.block_vmem_bytes(a, 8)
    assert b1 > b4 > b8 > 0
    # ceil-division over folded 128-lane rows: within 2x of linear
    assert b1 <= 4 * b4 <= 8 * b1


def test_mesh_supported_beyond_single_chip_envelope(monkeypatch):
    """The capacity story: pick a budget between the per-shard block
    claim and the single-chip claim — the single-chip gate must refuse
    while the 8-shard mesh gate admits. (Needs > 128 nodes: one folded
    128-lane row is the minimum block and cannot subdivide.)"""
    a = f32_arrays(multi_queue(600, 640, n_queues=3, tasks_per_job=6, seed=2))
    lo = pallas_solve.block_vmem_bytes(a, 8)
    hi = pallas_solve.block_vmem_bytes(a, 1)
    assert lo < hi
    monkeypatch.setenv("KBT_VMEM_BUDGET", str((lo + hi) // 2))
    assert pallas_solve.mesh_supported(a, 8)
    assert not pallas_solve.mesh_supported(a, 1)


def test_action_beyond_envelope_stays_on_pallas_rung(monkeypatch):
    """Through the action: a budget too small for the single-chip Pallas
    claim still engages the mesh_pallas rung when the node block divided
    over the mesh fits — instead of degrading to the XLA twin."""
    def mk():
        return multi_queue(600, 640, n_queues=3, tasks_per_job=6, seed=2)

    a = f32_arrays(mk())
    lo = pallas_solve.block_vmem_bytes(a, 8)
    hi = pallas_solve.block_vmem_bytes(a, 1)
    assert lo < hi
    budget = str((lo + hi) // 2)
    monkeypatch.setenv("KBT_VMEM_BUDGET", budget)
    # beyond the single-chip envelope (the full-snapshot claim only
    # grows from the node-block claim), within the 8-shard envelope
    assert not pallas_solve.supported(a)
    assert pallas_solve.mesh_supported(a, 8)
    binds, tier, mesh_n = run_action(mk, "cpu:8")
    assert mesh_n == 8
    assert tier == "mesh_pallas"
    serial = run_serial(mk)
    assert binds == serial and len(binds) == 600


# -- degradation: the mesh_pallas breaker rung ----------------------------


def test_mesh_pallas_fault_degrades_to_sharded_xla():
    """An injected mesh_pallas solve failure must degrade to the mesh
    XLA rung within the cycle (binds still land, still correct) and
    record against the mesh_pallas breaker."""
    def mk():
        return multi_queue(600, 64, n_queues=3, tasks_per_job=6, seed=11)

    faults.registry.reset()
    faults.solver_ladder.reset()
    breaker = faults.solver_ladder.breakers["mesh_pallas"]
    try:
        faults.registry.arm("solve.mesh_pallas", count=1)
        binds, tier, mesh_n = run_action(mk, "cpu:8")
        assert mesh_n == 8
        assert tier == "sharded_xla", f"expected mesh XLA rung, got {tier}"
        assert breaker.failures >= 1
        serial = run_serial(mk)
        assert binds == serial and len(binds) == 600
    finally:
        faults.registry.reset()
        faults.solver_ladder.reset()


# -- K-deep batched exchange (ISSUE 13) -----------------------------------


@pytest.mark.parametrize("n_devices,k", [(2, 2), (4, 4), (8, 4)])
def test_batched_exchange_matches_xla_twin(n_devices, k):
    """The speculative K-deep exchange (one all-gather per K gang
    iterations, owner-shard validation replaying only invalidated
    iterations) must agree with the XLA twin on every assignment and
    actually commit iterations from batches."""
    a = f32_arrays(synthetic(120, 24, seed=3))
    ref = solve_allocate_state(a, None, enable_drf=True, enable_proportion=True)
    sp = ShardedPallasSolver(
        a, make_mesh(n_devices), enable_drf=True, enable_proportion=True,
        exchange_batch=k,
    )
    got = sp.solve(None)
    assert_assignment_equal(ref, got, ctx=f"mesh {n_devices} K={k}")
    assert sp.batched_iters > 0, "no gang iteration committed from a batch"


@pytest.mark.parametrize("n_devices", [2, 8])
def test_batched_exchange_multi_queue(n_devices):
    a = f32_arrays(multi_queue(96, 16, n_queues=3, tasks_per_job=6, seed=7))
    ref = solve_allocate_state(a, None, enable_drf=True, enable_proportion=True)
    sp = ShardedPallasSolver(
        a, make_mesh(n_devices), enable_drf=True, enable_proportion=True,
        exchange_batch=4,
    )
    got = sp.solve(None)
    assert_assignment_equal(ref, got, ctx=f"mq mesh {n_devices} K=4")
    assert sp.batched_iters > 0


def test_batched_exchange_pause_resume_through_action():
    """KBT_PIPELINE + KBT_EXCHANGE_BATCH through the real action routing,
    including the segmented pod-affinity pause/resume hybrid: binds must
    match the serial path, and the action must account the amortized
    iterations (the bench rows read the same counter)."""
    from kube_batch_tpu import pipeline
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    saved = {k: os.environ.get(k) for k in ("KBT_PIPELINE", "KBT_EXCHANGE_BATCH")}
    os.environ["KBT_PIPELINE"] = "1"
    os.environ["KBT_EXCHANGE_BATCH"] = "4"
    pipeline.reset()
    try:
        cache = FakeCache(_pod_affinity_cluster())
        ssn = open_session(
            cache,
            parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers,
            {"xla_allocate": {"mesh": "cpu:4"}},
        )
        action = XlaAllocateAction(dtype=np.float32)
        action.execute(ssn)
        close_session(ssn)
        assert action.last_mesh_size == 4
        assert action.last_solver_tier == "mesh_pallas"
        assert action.last_batched_iters > 0
        serial = run_serial(_pod_affinity_cluster)
        assert dict(cache.binder.binds) == serial and len(serial) == 12
    finally:
        pipeline.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_default_exchange_batch_env():
    """K defaults to 1 (no speculation) outside pipelined mode; inside,
    KBT_EXCHANGE_BATCH with a sane default and a clamp."""
    from kube_batch_tpu import pipeline
    from kube_batch_tpu.parallel.sharded_pallas import _default_exchange_batch

    saved = {k: os.environ.get(k) for k in ("KBT_PIPELINE", "KBT_EXCHANGE_BATCH")}
    try:
        os.environ.pop("KBT_PIPELINE", None)
        os.environ["KBT_EXCHANGE_BATCH"] = "8"
        assert _default_exchange_batch() == 1, "K>1 must require KBT_PIPELINE"
        os.environ["KBT_PIPELINE"] = "1"
        assert _default_exchange_batch() == 8
        os.environ.pop("KBT_EXCHANGE_BATCH", None)
        assert _default_exchange_batch() == 4
        os.environ["KBT_EXCHANGE_BATCH"] = "200"
        assert _default_exchange_batch() == 64
        os.environ["KBT_EXCHANGE_BATCH"] = "banana"
        assert _default_exchange_batch() == 4
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
