"""Test configuration: force a deterministic 8-device virtual CPU mesh so
multi-chip sharding tests run anywhere (the driver separately dry-runs the
multichip path).

The ambient environment may already have imported JAX pointed at real TPU
hardware (an axon sitecustomize sets JAX_PLATFORMS=axon and imports jax at
interpreter start), so env vars are too late — use jax.config.update:

- platform cpu: the serial ≡ XLA equivalence tests need deterministic
  IEEE arithmetic; TPU f32 division is approximate and can flip floor/tie
  boundaries against the serial python path;
- x64: float64 arrays make the XLA path bit-identical to the serial
  float64 path. The TPU bench path runs float32, which is exact for
  milli/MiB-granular quantities (see ops/encode.py).
"""

import os
import sys

# Must precede the first CPU-backend initialization.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The parity suites exist to exercise the device kernels: disable the
# size floor that would route their (deliberately small) snapshots to
# the serial action in production.
os.environ.setdefault("KBT_MIN_DEVICE_PAIRS", "0")

# Cache-mutation detector on for every tier-1 run (VERDICT row 58): the
# reference gates its whole unit suite on KUBE_CACHE_MUTATION_DETECTOR=true
# (hack/make-rules/test.sh:27-28); any test driving Scheduler.run_once
# gets the digest-before/verify-after guard over shared store objects.
os.environ.setdefault("KBT_CACHE_MUTATION_DETECTOR", "1")

# Persistent compile cache stays inside the repo (gitignored), not the
# developer's $HOME: warm across local runs, easy to wipe, no pollution.
os.environ.setdefault(
    "KBT_JAX_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".pytest_cache", "jax"),
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading

import pytest


@pytest.fixture(autouse=True)
def _no_leaked_threads():
    """Thread-lifecycle discipline at test granularity (the dynamic twin
    of the KBT-T001 static check): a test that starts a non-daemon
    thread must stop/join it before returning, or interpreter shutdown
    hangs on the whole suite's behalf.

    Zero-cost on the common path: the grace join only runs when a NEW
    non-daemon thread is still alive at teardown. Daemon leaks (pumps
    whose stop() the test deliberately skipped) are tolerated here —
    the analyzer's witness drive and the chaos suite police those.
    """
    from kube_batch_tpu.utils.race import leaked_threads, thread_snapshot

    before = thread_snapshot()
    yield
    fresh_nondaemon = [
        t for t in threading.enumerate()
        if t.ident not in before
        and not t.daemon
        and t is not threading.current_thread()
        and t.is_alive()
    ]
    if not fresh_nondaemon:
        return
    leaked = leaked_threads(before, grace_s=2.0, include_daemon=False)
    if leaked:
        pytest.fail(
            "leaked non-daemon thread(s) past teardown: "
            + ", ".join(t.name for t in leaked)
            + " — every start() needs a reachable bounded join/stop path",
            pytrace=False,
        )
