"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding tests run anywhere (the driver separately dry-runs the multichip
path). Must run before the first ``import jax`` anywhere in the test
process."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
