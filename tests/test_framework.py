"""Unit tests pinning the tiered dispatch semantics
(reference session_plugins.go:90-440) and Statement transactions
(statement.go:26-222)."""

import pytest

from kube_batch_tpu.api.types import TaskStatus, ValidateResult
from kube_batch_tpu.conf import PluginOption, Tier, apply_plugin_conf_defaults
from kube_batch_tpu.framework import (
    EventHandler,
    Plugin,
    cleanup_plugin_builders,
    open_session,
    register_plugin_builder,
)
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


def make_tier(*names, **flag_overrides):
    options = []
    for name in names:
        opt = PluginOption(name=name)
        for k, v in flag_overrides.get(name, {}).items() if isinstance(flag_overrides.get(name), dict) else []:
            setattr(opt, k, v)
        apply_plugin_conf_defaults(opt)
        options.append(opt)
    return Tier(plugins=options)


class RecordingPlugin(Plugin):
    """Registers whatever fns a test hands it."""

    def __init__(self, name, fns):
        self._name = name
        self._fns = fns

    @property
    def name(self):
        return self._name

    def on_session_open(self, ssn):
        for kind, fn in self._fns.items():
            getattr(ssn, f"add_{kind}")(self._name, fn)


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    cleanup_plugin_builders()
    # Re-register the built-ins for other test modules.
    from kube_batch_tpu.plugins.factory import register_all_plugins

    register_all_plugins()


def open_with(plugins, tiers, cluster=None):
    for name, fns in plugins.items():
        register_plugin_builder(
            name, lambda args, name=name, fns=fns: RecordingPlugin(name, fns)
        )
    cache = FakeCache(cluster or build_cluster([], []))
    return open_session(cache, tiers)


def two_job_cluster():
    pods = [
        build_pod(name="p1", group_name="j1", req=build_resource_list(cpu=1)),
        build_pod(name="p2", group_name="j2", req=build_resource_list(cpu=1)),
    ]
    groups = [build_pod_group("j1"), build_pod_group("j2")]
    nodes = [build_node("n1", build_resource_list(cpu=4, memory="4Gi", pods=10))]
    return build_cluster(pods, nodes, groups, [build_queue("default")])


class TestOrderDispatch:
    def test_first_nonzero_across_tiers_wins(self):
        # Tier 1 plugin says equal; tier 2 plugin decides.
        ssn = open_with(
            {
                "a": {"job_order_fn": lambda l, r: 0},
                "b": {"job_order_fn": lambda l, r: -1 if l.name == "j2" else 1},
            },
            [make_tier("a"), make_tier("b")],
            two_job_cluster(),
        )
        j1 = next(j for j in ssn.jobs.values() if j.name == "j1")
        j2 = next(j for j in ssn.jobs.values() if j.name == "j2")
        assert ssn.job_order_fn(j2, j1) is True
        assert ssn.job_order_fn(j1, j2) is False

    def test_earlier_tier_shadow_later(self):
        ssn = open_with(
            {
                "a": {"job_order_fn": lambda l, r: -1 if l.name == "j1" else 1},
                "b": {"job_order_fn": lambda l, r: -1 if l.name == "j2" else 1},
            },
            [make_tier("a"), make_tier("b")],
            two_job_cluster(),
        )
        j1 = next(j for j in ssn.jobs.values() if j.name == "j1")
        j2 = next(j for j in ssn.jobs.values() if j.name == "j2")
        assert ssn.job_order_fn(j1, j2) is True

    def test_fallback_creation_time_then_uid(self):
        cluster = two_job_cluster()
        jobs = list(cluster.jobs.values())
        jobs[0].creation_timestamp = 100.0
        jobs[1].creation_timestamp = 50.0
        ssn = open_with({}, [], cluster)
        younger = next(j for j in ssn.jobs.values() if j.creation_timestamp == 50.0)
        older = next(j for j in ssn.jobs.values() if j.creation_timestamp == 100.0)
        assert ssn.job_order_fn(younger, older) is True
        # Equal timestamps: UID decides.
        older.creation_timestamp = 50.0
        lo, hi = sorted([younger, older], key=lambda j: j.uid)
        assert ssn.job_order_fn(lo, hi) is True
        assert ssn.job_order_fn(hi, lo) is False

    def test_disabled_flag_skips_plugin(self):
        tier = Tier(plugins=[PluginOption(name="a", enabled_job_order=False)])
        apply_plugin_conf_defaults(tier.plugins[0])
        ssn = open_with(
            {"a": {"job_order_fn": lambda l, r: -1 if l.name == "j2" else 1}},
            [tier],
            two_job_cluster(),
        )
        j1 = next(j for j in ssn.jobs.values() if j.name == "j1")
        j2 = next(j for j in ssn.jobs.values() if j.name == "j2")
        j1.creation_timestamp = 1.0
        j2.creation_timestamp = 2.0
        # Plugin would favor j2, but it's disabled -> creation time wins.
        assert ssn.job_order_fn(j1, j2) is True


class TestPredicateAndScoreDispatch:
    def test_predicates_and_semantics(self):
        calls = []

        def ok(task, node):
            calls.append("ok")

        def fail(task, node):
            raise RuntimeError("nope")

        ssn = open_with(
            {"a": {"predicate_fn": ok}, "b": {"predicate_fn": fail}},
            [make_tier("a", "b")],
            two_job_cluster(),
        )
        task = next(iter(next(iter(ssn.jobs.values())).tasks.values()))
        node = next(iter(ssn.nodes.values()))
        with pytest.raises(RuntimeError):
            ssn.predicate_fn(task, node)
        assert calls == ["ok"]  # AND short-circuits at first failure

    def test_node_order_sums_across_plugins(self):
        ssn = open_with(
            {
                "a": {"node_order_fn": lambda t, n: 3.0},
                "b": {"node_order_fn": lambda t, n: 4.0},
            },
            [make_tier("a"), make_tier("b")],
            two_job_cluster(),
        )
        task = next(iter(next(iter(ssn.jobs.values())).tasks.values()))
        node = next(iter(ssn.nodes.values()))
        assert ssn.node_order_fn(task, node) == 7.0


class TestVictimDispatch:
    def _session(self, plugin_victims, tiers):
        fns = {}
        for name, picker in plugin_victims.items():
            fns[name] = {"preemptable_fn": picker}
        return open_with(fns, tiers, two_job_cluster())

    def test_intersection_within_tier(self):
        ssn = self._session(
            {
                "a": lambda p, cands: [c for c in cands if c.name in ("v1", "v2")],
                "b": lambda p, cands: [c for c in cands if c.name in ("v2", "v3")],
            },
            [make_tier("a", "b")],
        )
        from kube_batch_tpu.testing import build_task

        preemptor = build_task(name="p")
        cands = [build_task(name=n) for n in ("v1", "v2", "v3")]
        victims = ssn.preemptable(preemptor, cands)
        assert [v.name for v in victims] == ["v2"]

    def test_empty_tier_result_falls_through(self):
        """Go parity: plugins return nil slices when they select nothing,
        so a zero-victim tier defers to the next tier
        (session_plugins.go:126-131 with nil-when-empty slices)."""
        ssn = self._session(
            {
                "a": lambda p, cands: [],  # "no victims" == nil in Go
                "b": lambda p, cands: list(cands),
            },
            [make_tier("a"), make_tier("b")],
        )
        from kube_batch_tpu.testing import build_task

        victims = ssn.preemptable(build_task(name="p"), [build_task(name="v1")])
        assert [v.name for v in victims] == ["v1"]

    def test_empty_intersection_falls_through(self):
        """Disjoint picks within a tier -> empty intersection -> next tier
        decides (the Go intersection slice is nil when empty)."""
        ssn = self._session(
            {
                "a": lambda p, cands: [c for c in cands if c.name == "v1"],
                "b": lambda p, cands: [c for c in cands if c.name == "v2"],
                "c": lambda p, cands: list(cands),
            },
            [make_tier("a", "b"), make_tier("c")],
        )
        from kube_batch_tpu.testing import build_task

        cands = [build_task(name="v1"), build_task(name="v2")]
        victims = ssn.preemptable(build_task(name="p"), cands)
        assert sorted(v.name for v in victims) == ["v1", "v2"]

    def test_tier_without_fns_defers(self):
        ssn = self._session(
            {"b": lambda p, cands: list(cands)},
            [make_tier("a"), make_tier("b")],
        )
        from kube_batch_tpu.testing import build_task

        victims = ssn.preemptable(build_task(name="p"), [build_task(name="v1")])
        assert [v.name for v in victims] == ["v1"]


class TestValidateDispatch:
    def test_job_valid_first_failure(self):
        ssn = open_with(
            {
                "a": {"job_valid_fn": lambda job: None},
                "b": {
                    "job_valid_fn": lambda job: ValidateResult(False, "r", "m")
                    if job.name == "j2"
                    else None
                },
            },
            [make_tier("a", "b")],
            two_job_cluster(),
        )
        # j2 was rejected at session open and removed (gate).
        assert sorted(j.name for j in ssn.jobs.values()) == ["j1"]

    def test_overused_or(self):
        ssn = open_with(
            {
                "a": {"overused_fn": lambda q: False},
                "b": {"overused_fn": lambda q: True},
            },
            [make_tier("a", "b")],
            two_job_cluster(),
        )
        queue = next(iter(ssn.queues.values()))
        assert ssn.overused(queue) is True

    def test_job_ready_and(self):
        ssn = open_with(
            {
                "a": {"job_ready_fn": lambda j: True},
                "b": {"job_ready_fn": lambda j: False},
            },
            [make_tier("a", "b")],
            two_job_cluster(),
        )
        job = next(iter(ssn.jobs.values()))
        assert ssn.job_ready(job) is False


class TestStatement:
    def _running_cluster(self):
        pods = [
            build_pod(
                name="victim",
                group_name="jv",
                req=build_resource_list(cpu=1),
                node_name="n1",
            ),
            build_pod(name="starved", group_name="js", req=build_resource_list(cpu=1)),
        ]
        from kube_batch_tpu.apis.types import PodPhase

        pods[0].phase = PodPhase.RUNNING
        groups = [build_pod_group("jv"), build_pod_group("js")]
        nodes = [build_node("n1", build_resource_list(cpu=1, memory="1Gi", pods=10))]
        return build_cluster(pods, nodes, groups, [build_queue("default")])

    def test_discard_restores_session_state(self):
        ssn = open_with({}, [], self._running_cluster())
        victim_job = next(j for j in ssn.jobs.values() if j.name == "jv")
        starved_job = next(j for j in ssn.jobs.values() if j.name == "js")
        victim = next(iter(victim_job.tasks.values()))
        starved = next(iter(starved_job.tasks.values()))
        node = ssn.nodes["n1"]
        idle_before = node.idle.clone()

        stmt = ssn.statement()
        stmt.evict(victim, "test")
        assert victim.status == TaskStatus.RELEASING
        stmt.pipeline(starved, "n1")
        assert starved.status == TaskStatus.PIPELINED

        stmt.discard()
        assert victim.status == TaskStatus.RUNNING
        assert starved.status == TaskStatus.PENDING
        assert starved.node_name == ""
        assert node.idle == idle_before
        assert ssn.cache.evictor.evicts == []

    def test_commit_replays_evictions_to_cache(self):
        ssn = open_with({}, [], self._running_cluster())
        victim_job = next(j for j in ssn.jobs.values() if j.name == "jv")
        victim = next(iter(victim_job.tasks.values()))
        stmt = ssn.statement()
        stmt.evict(victim, "test")
        stmt.commit()
        assert ssn.cache.evictor.evicts == ["default/victim"]

    def test_event_handlers_fire_and_unwind(self):
        events = []
        ssn = open_with({}, [], self._running_cluster())
        ssn.add_event_handler(
            EventHandler(
                allocate_func=lambda e: events.append(("alloc", e.task.name)),
                deallocate_func=lambda e: events.append(("dealloc", e.task.name)),
            )
        )
        victim_job = next(j for j in ssn.jobs.values() if j.name == "jv")
        victim = next(iter(victim_job.tasks.values()))
        stmt = ssn.statement()
        stmt.evict(victim, "test")
        stmt.discard()
        assert events == [("dealloc", "victim"), ("alloc", "victim")]
