"""SchedulerCache unit tests: feed store mutations, assert the mirror
(the pattern of reference cache/cache_test.go:128-227, extended to the
write side, resync, GC, and snapshot policy)."""

from __future__ import annotations

import time

import pytest

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import (
    GROUP_NAME_ANNOTATION_KEY,
    ObjectMeta,
    PodDisruptionBudget,
    PodGroupPhase,
    PodPhase,
    PriorityClass,
)
from kube_batch_tpu.cache import ClusterStore, SchedulerCache, shadow_pod_group
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource,
    build_resource_list,
)


def wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def store():
    return ClusterStore()


@pytest.fixture
def cache(store):
    sc = SchedulerCache(store)
    yield sc
    sc.stop()


def test_add_pod_accounts_on_node(store, cache):
    store.create_node(build_node("n1", build_resource_list(cpu=8, memory="16Gi", pods=100)))
    store.create_pod(
        build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                  req=build_resource_list(cpu=2, memory="4Gi"))
    )
    ni = cache.nodes["n1"]
    assert ni.used == build_resource(cpu=2, memory="4Gi")
    assert ni.idle == build_resource(cpu=6, memory="12Gi")
    assert len(ni.tasks) == 1


def test_node_arriving_after_pods_replays_accounting(store, cache):
    """Pods seen before their node: accounting lands once the node shows
    up (reference event_handlers.go:70-88 + node_info SetNode)."""
    store.create_pod(
        build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                  req=build_resource_list(cpu=2))
    )
    assert cache.nodes["n1"].node is None  # placeholder, no capacity yet
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    ni = cache.nodes["n1"]
    assert ni.used == build_resource(cpu=2)
    assert ni.idle == build_resource(cpu=6)


def test_shadow_pod_group_for_annotationless_pod(store, cache):
    store.create_pod(build_pod(name="solo", req=build_resource_list(cpu=1)))
    assert len(cache.jobs) == 1
    job = next(iter(cache.jobs.values()))
    assert shadow_pod_group(job.pod_group)
    assert job.min_available == 1
    assert job.queue == "default"
    assert job.pod_group.status.phase == PodGroupPhase.INQUEUE


def test_shadow_group_shares_controller(store, cache):
    """Sibling pods of one controller share one shadow job
    (reference cache/util.go:43-49 GetController)."""
    for i in range(3):
        pod = build_pod(name=f"rs-{i}", req=build_resource_list(cpu=1))
        pod.metadata.owner_job = "rs-frontend"
        store.create_pod(pod)
    assert len(cache.jobs) == 1
    assert len(next(iter(cache.jobs.values())).tasks) == 3


def test_other_scheduler_pending_pod_filtered(store, cache):
    store.create_pod(build_pod(name="alien", scheduler_name="default-scheduler"))
    assert not cache.jobs


def test_other_scheduler_running_pod_occupies_node(store, cache):
    """Non-pending pods pass the filter regardless of scheduler — they
    hold node resources (reference cache.go:245-266)."""
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    store.create_pod(
        build_pod(name="alien", node_name="n1", phase=PodPhase.RUNNING,
                  scheduler_name="default-scheduler", req=build_resource_list(cpu=3))
    )
    assert cache.nodes["n1"].idle == build_resource(cpu=5)
    assert not cache.jobs  # no shadow job for foreign pods


def test_pod_group_binds_tasks_and_default_queue(store, cache):
    store.create_pod_group(build_pod_group("pg1", min_member=2))
    store.create_pod(build_pod(name="m1", group_name="pg1", req=build_resource_list(cpu=1)))
    store.create_pod(build_pod(name="m2", group_name="pg1", req=build_resource_list(cpu=1)))
    job = cache.jobs["default/pg1"]
    assert job.min_available == 2
    assert len(job.tasks) == 2
    assert job.queue == "default"  # empty spec.queue -> defaultQueue


def test_pdb_gang_source(store, cache):
    pdb = PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb1", namespace="default"), min_available=2
    )
    store.create_pdb(pdb)
    job = cache.jobs["default/pdb1"]
    assert job.pdb is pdb
    assert job.min_available == 2
    assert job.queue == "default"


def test_snapshot_priority_class_resolution(store, cache):
    store.create_queue(build_queue("default"))
    store.create_priority_class(
        PriorityClass(metadata=ObjectMeta(name="high"), value=1000)
    )
    store.create_priority_class(
        PriorityClass(metadata=ObjectMeta(name="base"), value=7, global_default=True)
    )
    pg_hi = build_pod_group("hi")
    pg_hi.spec.priority_class_name = "high"
    store.create_pod_group(pg_hi)
    store.create_pod_group(build_pod_group("lo"))
    store.create_pod(build_pod(name="h", group_name="hi"))
    store.create_pod(build_pod(name="l", group_name="lo"))

    snap = cache.snapshot()
    assert snap.jobs["default/hi"].priority == 1000
    assert snap.jobs["default/lo"].priority == 7  # global default

    store.delete_priority_class("base")
    snap = cache.snapshot()
    assert snap.jobs["default/lo"].priority == 0


def test_snapshot_skips_job_with_missing_queue(store, cache):
    store.create_queue(build_queue("default"))
    pg = build_pod_group("orphan", queue="nonexistent")
    store.create_pod_group(pg)
    store.create_pod(build_pod(name="o", group_name="orphan"))
    snap = cache.snapshot()
    assert "default/orphan" not in snap.jobs
    # ...and jobs in a live queue survive.
    store.create_pod_group(build_pod_group("ok", queue="default"))
    store.create_pod(build_pod(name="k", group_name="ok"))
    assert "default/ok" in cache.snapshot().jobs


def test_snapshot_is_deep_clone(store, cache):
    store.create_queue(build_queue("default"))
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    store.create_pod(build_pod(name="p", req=build_resource_list(cpu=1)))
    snap = cache.snapshot()
    job = next(iter(snap.jobs.values()))
    task = next(iter(job.tasks.values()))
    job.update_task_status(task, TaskStatus.ALLOCATED)
    snap.nodes["n1"].add_task(task)
    # The cache mirror is untouched by session mutations.
    cached = next(iter(cache.jobs.values()))
    assert next(iter(cached.tasks.values())).status == TaskStatus.PENDING
    assert cache.nodes["n1"].idle == build_resource(cpu=8)


def test_bind_round_trip(store, cache):
    """bind() flips the mirror to Binding, the async store write sets
    pod.node_name, and the resulting update event lands the task Bound
    on the node (reference cache.go:404-448)."""
    cache.run()
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    store.create_pod(build_pod(name="p1", req=build_resource_list(cpu=2)))
    job = next(iter(cache.jobs.values()))
    task = next(iter(job.tasks.values()))

    cache.bind(task, "n1")
    wait_until(
        lambda: store.get_pod("default", "p1").node_name == "n1",
        what="bind write-back",
    )
    wait_until(
        lambda: next(iter(next(iter(cache.jobs.values())).tasks.values())).status
        == TaskStatus.BOUND,
        what="Binding -> Bound round trip",
    )
    assert cache.nodes["n1"].used == build_resource(cpu=2)
    assert len(cache.nodes["n1"].tasks) == 1


class FailingBinder:
    def __init__(self, store, fail_times):
        self._inner_store = store
        self.fail_times = fail_times
        self.calls = 0

    def bind(self, pod, hostname):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise RuntimeError("injected bind failure")
        import dataclasses

        self._inner_store.update_pod(dataclasses.replace(pod, node_name=hostname))


def test_failed_bind_resyncs_task(store):
    """A failed bind re-enters through errTasks: the task returns to
    Pending and is schedulable again (reference cache.go:512-534)."""
    binder = FailingBinder(store, fail_times=10**9)
    sc = SchedulerCache(store, binder=binder)
    sc.run()
    try:
        store.create_node(build_node("n1", build_resource_list(cpu=8)))
        store.create_pod(build_pod(name="p1", req=build_resource_list(cpu=2)))
        task = next(iter(next(iter(sc.jobs.values())).tasks.values()))
        sc.bind(task, "n1")
        wait_until(lambda: binder.calls >= 1, what="binder attempt")
        wait_until(
            lambda: next(iter(next(iter(sc.jobs.values())).tasks.values())).status
            == TaskStatus.PENDING,
            what="resync back to Pending",
        )
        # Node accounting rolled back too.
        assert sc.nodes["n1"].used == build_resource()
        assert store.get_pod("default", "p1").node_name == ""
    finally:
        sc.stop()


def test_evict_releases_then_deletes(store, cache):
    cache.run()
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    store.create_pod(
        build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                  req=build_resource_list(cpu=2))
    )
    task = next(iter(next(iter(cache.jobs.values())).tasks.values()))
    cache.evict(task, "preempted")
    wait_until(lambda: store.get_pod("default", "p1") is None, what="evict delete")
    wait_until(lambda: not cache.nodes["n1"].tasks, what="node cleanup")
    assert cache.nodes["n1"].idle == build_resource(cpu=8)


def test_terminated_job_gc(store, cache):
    """Deleting the PodGroup and all pods garbage-collects the job
    through the deletedJobs queue (reference cache.go:480-510)."""
    cache.run()
    store.create_pod_group(build_pod_group("pg1"))
    store.create_pod(build_pod(name="m1", group_name="pg1"))
    assert "default/pg1" in cache.jobs
    store.delete_pod("default", "m1")
    store.delete_pod_group("default", "pg1")
    wait_until(lambda: "default/pg1" not in cache.jobs, what="job GC")


def test_shadow_job_gc_after_pod_delete(store, cache):
    """Shadow jobs are GC'd once their last pod goes away — the shadow
    PodGroup lives only in the cache, so it counts as absent for
    job_terminated (divergence from reference api/helpers.go:101-106)."""
    cache.run()
    store.create_pod(build_pod(name="solo", req=build_resource_list(cpu=1)))
    assert len(cache.jobs) == 1
    store.delete_pod("default", "solo")
    wait_until(lambda: not cache.jobs, what="shadow job GC")


def test_pdb_does_not_stomp_podgroup_queue(store, cache):
    pg = build_pod_group("pg1", queue="research")
    store.create_pod_group(pg)
    pdb = PodDisruptionBudget(
        metadata=ObjectMeta(name="pdb1", namespace="default", owner_job="default/pg1"),
        min_available=2,
    )
    store.create_pdb(pdb)
    assert cache.jobs["default/pg1"].queue == "research"


def test_unschedulable_condition_writes_through_store(store, cache):
    """record_job_status_event posts PodScheduled=False through the
    store, not onto a possibly-stale cached pod object."""
    store.create_queue(build_queue("default"))
    store.create_pod(build_pod(name="p1", req=build_resource_list(cpu=1)))
    job = next(iter(cache.jobs.values()))
    cache.record_job_status_event(job)
    conds = store.get_pod("default", "p1").conditions
    assert any(c.type == "PodScheduled" and c.status == "False" for c in conds)


def test_node_update_reconciles_resources(store, cache):
    node = build_node("n1", build_resource_list(cpu=8))
    store.create_node(node)
    store.create_pod(
        build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                  req=build_resource_list(cpu=2))
    )
    bigger = build_node("n1", build_resource_list(cpu=16))
    store.update_node(bigger)
    ni = cache.nodes["n1"]
    assert ni.idle == build_resource(cpu=14)
    assert ni.used == build_resource(cpu=2)


def test_delete_node(store, cache):
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    store.delete_node("n1")
    assert "n1" not in cache.nodes


def test_pod_update_resize_reaccounts(store, cache):
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    pod = build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=2))
    store.create_pod(pod)
    resized = build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                        req=build_resource_list(cpu=4))
    resized.metadata.uid = pod.metadata.uid
    store.update_pod(resized)
    assert cache.nodes["n1"].used == build_resource(cpu=4)
    job = next(iter(cache.jobs.values()))
    assert len(job.tasks) == 1


def test_shadow_job_member_delete_does_not_strand(store, cache):
    """Deleting a shadow-group pod removes it from the job too (the
    reference leaks these, event_handlers.go:160-180; see
    cache._resolve_shadow_job)."""
    store.create_pod(build_pod(name="solo", req=build_resource_list(cpu=1)))
    job = next(iter(cache.jobs.values()))
    assert len(job.tasks) == 1
    store.delete_pod("default", "solo")
    assert not job.tasks


def test_terminated_pod_lifecycle_does_not_strand_task(store, cache):
    """A Succeeded pod (never resident on the node mirror) can still be
    updated and deleted: update keeps the task, delete GCs the job."""
    cache.run()
    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    pod = build_pod(name="p1", node_name="n1", phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=2))
    store.create_pod(pod)
    done = build_pod(name="p1", node_name="n1", phase=PodPhase.SUCCEEDED,
                     req=build_resource_list(cpu=2))
    done.metadata.uid = pod.metadata.uid
    store.update_pod(done)
    assert cache.nodes["n1"].idle == build_resource(cpu=8)  # released
    job = next(iter(cache.jobs.values()))
    assert len(job.tasks) == 1  # task survives in Succeeded
    # Another update (e.g. a condition append) must not strand it.
    store.update_pod(done)
    assert len(next(iter(cache.jobs.values())).tasks) == 1
    store.delete_pod("default", "p1")
    wait_until(lambda: not cache.jobs, what="terminated shadow job GC")


def test_node_condition_change_reaches_mirror(store, cache):
    """Ready/pressure flips refresh the cached Node even when nothing
    else changed, so predicates see them next snapshot."""
    from kube_batch_tpu.apis.types import NodeCondition

    store.create_node(build_node("n1", build_resource_list(cpu=8)))
    broken = build_node("n1", build_resource_list(cpu=8))
    broken.conditions = [NodeCondition(type="Ready", status="False")]
    store.update_node(broken)
    assert not cache.nodes["n1"].node.ready()


def test_cache_stop_then_run_resyncs_again(store):
    """stop() then run() must leave the resync machinery live (the
    retry queues reopen)."""
    # past the in-place retry budget (KBT_WRITE_RETRIES, default 2), so
    # the failure reaches the errTasks resync machinery under test —
    # fewer failures would now be absorbed by the retry-with-jitter rung
    binder = FailingBinder(store, fail_times=3)
    sc = SchedulerCache(store, binder=binder)
    sc.run()
    sc.stop()
    sc.run()
    try:
        store.create_node(build_node("n1", build_resource_list(cpu=8)))
        store.create_pod(build_pod(name="p1", req=build_resource_list(cpu=2)))
        task = next(iter(next(iter(sc.jobs.values())).tasks.values()))
        sc.bind(task, "n1")  # first attempt fails -> resync -> retried later
        wait_until(lambda: binder.calls >= 1, what="first bind attempt")
        wait_until(
            lambda: next(iter(next(iter(sc.jobs.values())).tasks.values())).status
            == TaskStatus.PENDING,
            what="resync after restart",
        )
    finally:
        sc.stop()


def test_group_annotation_requires_podgroup_to_snapshot(store, cache):
    """An annotated pod whose PodGroup never arrives builds a spec-less
    job that snapshot() skips (reference cache.go:545-552)."""
    store.create_queue(build_queue("default"))
    pod = build_pod(name="waiting", group_name="late-pg")
    store.create_pod(pod)
    assert "default/late-pg" in cache.jobs
    assert "default/late-pg" not in cache.snapshot().jobs
    store.create_pod_group(build_pod_group("late-pg"))
    assert "default/late-pg" in cache.snapshot().jobs


def test_annotated_pod_survives_group_annotation(store, cache):
    pod = build_pod(name="g1", group_name="pg1", req=build_resource_list(cpu=1))
    assert GROUP_NAME_ANNOTATION_KEY in pod.metadata.annotations
    store.create_pod_group(build_pod_group("pg1"))
    store.create_pod(pod)
    assert len(cache.jobs["default/pg1"].tasks) == 1
