"""Dynamic resharding (ISSUE 16): leased shard slots, survivor
adoption, graceful handoff and the reclaim protocol.

The headline e2e is the kill drill: SIGKILL one shard owner
mid-``bind_many`` at N=4 (dying binder through the optimistic path +
``ShardSlotManager.kill()`` so the lease must expire on the arbiter's
clock) and require a survivor to adopt the orphaned slot within the
lease window with zero lost and zero duplicate binds, union parity
against a single-scheduler twin, and a clean fsck. Around it, the
deterministic pieces: the fsck unowned-slot check, ``set_owned_slots``
backfill/dedupe, lease-flap single-ownership, breaker-backed adoption
failure, handoff abort-on-fault, the reclaim protocol, and the
streaming adopted-keys seeding.
"""

from __future__ import annotations

import pytest

from kube_batch_tpu import faults, metrics
from kube_batch_tpu.api.job_info import job_key
from kube_batch_tpu.cache import ClusterStore, EventHandler, SchedulerCache
from kube_batch_tpu.cache.store import LEASES, PODS
from kube_batch_tpu.federation import (
    FederatedCache,
    ShardSlotManager,
    fsck,
    parse_slot_lease_name,
    plan_rebalance,
    reclaim_lease_name,
    shard_index,
    shard_journal_path,
    slot_lease_name,
    smoke_kill_one,
)
from kube_batch_tpu.recovery import WriteIntentJournal
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def seed_store(store, nodes=2, cpu=16, gangs=(), members=2):
    if store.get("queues", "default") is None:
        store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(
                f"n{i}", build_resource_list(cpu=cpu, memory=f"{cpu}Gi", pods=64)
            )
        )
    for g in gangs:
        store.create_pod_group(build_pod_group(g, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{g}-p{m}", group_name=g,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def gangs_for_slots(shards: int, per_slot: int = 1) -> dict[int, list[str]]:
    """Deterministically pick gang names hashing into each slot (crc32
    is stable, so the picks are stable too)."""
    out: dict[int, list[str]] = {s: [] for s in range(shards)}
    i = 0
    while any(len(v) < per_slot for v in out.values()):
        name = f"g{i}"
        slot = shard_index(job_key("default", name), shards)
        if len(out[slot]) < per_slot:
            out[slot].append(name)
        i += 1
    return out


def make_pair(store, tmp_path, shards=2):
    """Two FederatedCaches + managers (no loops started: tests drive
    ``step()``/``handoff()`` directly for determinism)."""
    caches, mgrs = [], []
    for i in range(shards):
        cache = FederatedCache(store, shard=i, shards=shards, shard_key="gang")
        mgr = ShardSlotManager(
            store, cache, identity=f"mgr-{i}",
            lease_s=60.0, renew_s=1.0, adopt=True,
            journal_dir=str(tmp_path), grace_s=0.0, rebalance=0,
        )
        store.try_acquire_lease(slot_lease_name(i), mgr.identity, mgr.lease_s)
        mgr._set_owned({i})
        caches.append(cache)
        mgrs.append(mgr)
    return caches, mgrs


# -- slot-lease naming --------------------------------------------------------


def test_slot_lease_name_round_trip():
    assert parse_slot_lease_name(slot_lease_name(3)) == 3
    assert parse_slot_lease_name("shard-slot-0") == 0
    assert parse_slot_lease_name("not-a-slot") is None
    assert parse_slot_lease_name("shard-slot-x") is None
    # reclaim leases are NOT slot leases (they must never wake adoption)
    assert parse_slot_lease_name(reclaim_lease_name(3)) is None


def test_plan_rebalance_sheds_most_recent_adoption_only():
    # below threshold, no adopted slots, or primary-only: nothing to shed
    assert plan_rebalance({0}, 0, [], 100.0, 10.0) is None
    assert plan_rebalance({0, 1}, 0, [1], 5.0, 10.0) is None
    assert plan_rebalance({0, 1}, 0, [1], 50.0, 0.0) is None  # disabled
    # most recently adopted non-primary slot goes first
    assert plan_rebalance({0, 1, 2}, 0, [1, 2], 50.0, 10.0) == 2
    assert plan_rebalance({0, 1, 2}, 0, [2, 1], 50.0, 10.0) == 1


# -- the kill drill (the acceptance e2e) --------------------------------------


def test_kill_one_shard_owner_adopts_within_lease_window():
    """SIGKILL mid-bind_many at N=4: a survivor adopts within the lease
    window, zero lost/duplicate binds, union parity vs the twin, fsck
    clean after recovery."""
    out = smoke_kill_one(shards=4, gangs=16, members=2)
    assert out["ok"], out
    assert out["adopter"] is not None
    assert out["double_owned"] == 1, "orphaned slot adopted more than once"
    assert out["takeover_s"] <= out["takeover_window_s"], out
    assert out["mttr_s"] is not None
    assert out["double_binds"] == 0
    assert out["exactly_once"]
    assert out["union_parity"]
    assert out["fsck_violations"] == []
    assert out["bound"] == out["pods"]


# -- fsck: unowned slots ------------------------------------------------------


def test_fsck_reports_unowned_slot_with_pending_pods():
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0], picks[1][0]])
    # slot 1 live, slot 0's lease expired long ago
    store.try_acquire_lease(slot_lease_name(0), "dead", 5.0, now=100.0)
    store.try_acquire_lease(slot_lease_name(1), "alive", 5.0, now=200.0)
    violations = fsck(store, shard_key="gang", now=200.0)
    assert any(v.startswith("unowned slot 0:") for v in violations), violations
    assert not any(v.startswith("unowned slot 1:") for v in violations)
    # a released slot (graceful shutdown, nobody adopted yet) is also
    # unowned work
    store.try_acquire_lease(slot_lease_name(0), "dead", 5.0, now=201.0)
    store.release_lease(slot_lease_name(0), "dead")
    violations = fsck(store, shard_key="gang", now=202.0)
    assert any("released" in v for v in violations if v.startswith("unowned slot 0"))
    # once someone live holds it, the check clears
    store.try_acquire_lease(slot_lease_name(0), "survivor", 5.0, now=203.0)
    assert fsck(store, shard_key="gang", now=203.0) == []


def test_fsck_without_slot_leases_skips_the_check():
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0]])
    assert fsck(store, shard_key="gang") == []  # static-map world: no leases


# -- FederatedCache.set_owned_slots ------------------------------------------


def test_set_owned_slots_backfills_and_dedupes():
    store = ClusterStore()
    picks = gangs_for_slots(2, per_slot=2)
    seed_store(store, gangs=picks[0] + picks[1], members=2)
    cache = FederatedCache(store, shard=0, shards=2, shard_key="gang")
    # primary slot only: slot-1 pods are filtered out of the mirror
    assert all(not cache._has_task(p) for p in store.list(PODS)
               if p.name.startswith(tuple(picks[1])))
    # pre-ingest ONE slot-1 pod (an event that raced the flip): the
    # backfill must dedupe it, not double-add
    raced = next(
        p for p in store.list(PODS) if p.name.startswith(picks[1][0])
    )
    cache.add_pod(raced)
    change = cache.set_owned_slots({0, 1})
    assert change["added"] == {1}
    assert change["adopted_pods"] == 3  # 4 slot-1 pods minus the raced one
    assert change["adopted_gangs"] == {f"default/{g}" for g in picks[1]}
    assert cache.owned_slots == frozenset({0, 1})
    # idempotent: same set is a no-op
    again = cache.set_owned_slots({0, 1})
    assert again["added"] == set() and again["adopted_pods"] == 0
    # narrowing drops the slot's tasks from the mirror
    change = cache.set_owned_slots({0})
    assert change["removed"] == {1}
    assert change["removed_gangs"] == {f"default/{g}" for g in picks[1]}
    assert all(not cache._has_task(p) for p in store.list(PODS)
               if p.name.startswith(tuple(picks[1])))
    with pytest.raises(ValueError):
        cache.set_owned_slots({0, 7})


# -- lease flap ---------------------------------------------------------------


def test_lease_flap_drops_one_renewal_without_double_adoption():
    store = ClusterStore()
    tmp = None
    caches, mgrs = [], []
    for i in range(2):
        cache = FederatedCache(store, shard=i, shards=2, shard_key="gang")
        mgr = ShardSlotManager(
            store, cache, identity=f"flap-{i}",
            lease_s=60.0, renew_s=1.0, adopt=True,
            journal_dir=tmp, grace_s=0.0, rebalance=0,
        )
        store.try_acquire_lease(slot_lease_name(i), mgr.identity, 60.0)
        mgr._set_owned({i})
        caches.append(cache)
        mgrs.append(mgr)
    before = store.get(LEASES, slot_lease_name(0)).lease_transitions
    faults.registry.arm("shard.lease_flap", count=1)
    mgrs[0].step()  # renewal round dropped entirely
    mgrs[1].step()  # peer probes: slot 0's lease is stale-but-live
    lease = store.get(LEASES, slot_lease_name(0))
    assert lease.holder_identity == "flap-0"
    assert 0 not in mgrs[1].owned_slots()
    mgrs[0].step()  # next round reacquires: same holder, no transition
    lease = store.get(LEASES, slot_lease_name(0))
    assert lease.holder_identity == "flap-0"
    assert lease.lease_transitions == before


# -- adoption: breaker-backed failure ----------------------------------------


def test_injected_adopt_failure_releases_slot_then_retry_succeeds(tmp_path):
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0], picks[1][0]])
    caches, mgrs = make_pair(store, tmp_path)
    # slot 0's owner dies: release-without-renew is simulated by just
    # deleting its renewals — expire it via a fresh short lease
    store.release_lease(slot_lease_name(0), "mgr-0")
    before = dict(metrics.shard_adoptions.samples())
    faults.registry.arm("shard.adopt", count=1)
    mgrs[1].step()  # probe wins the lease, takeover fails, slot released
    lease = store.get(LEASES, slot_lease_name(0))
    assert not lease.holder_identity, "failed adoption must release the slot"
    assert 0 not in mgrs[1].owned_slots()
    failed = metrics.shard_adoptions.samples().get((("outcome", "failed"),), 0)
    assert failed == before.get((("outcome", "failed"),), 0) + 1
    mgrs[1].step()  # fault exhausted: the retry adopts for real
    assert 0 in mgrs[1].owned_slots()
    assert store.get(LEASES, slot_lease_name(0)).holder_identity == "mgr-1"
    assert caches[1].owned_slots == frozenset({0, 1})


def test_open_breaker_suppresses_adoption_and_releases(tmp_path):
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0], picks[1][0]])
    caches, mgrs = make_pair(store, tmp_path)
    store.release_lease(slot_lease_name(0), "mgr-0")
    for _ in range(3):
        mgrs[1]._breaker.record_failure()
    assert not mgrs[1]._breaker.allow()
    before = metrics.shard_adoptions.samples().get(
        (("outcome", "flap_suppressed"),), 0
    )
    mgrs[1].step()
    after = metrics.shard_adoptions.samples().get(
        (("outcome", "flap_suppressed"),), 0
    )
    assert after == before + 1
    assert 0 not in mgrs[1].owned_slots()
    assert not store.get(LEASES, slot_lease_name(0)).holder_identity


# -- handoff ------------------------------------------------------------------


def test_handoff_moves_slot_and_backlog_to_peer(tmp_path):
    store = ClusterStore()
    picks = gangs_for_slots(2, per_slot=2)
    seed_store(store, gangs=picks[0] + picks[1], members=2)
    caches, mgrs = make_pair(store, tmp_path)
    # adopt slot 1 onto mgr-0 first (simulating an earlier takeover)
    store.release_lease(slot_lease_name(1), "mgr-1")
    mgrs[1]._set_owned(set())
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}
    assert caches[0].owned_slots == frozenset({0, 1})
    # planned move back: drain + release, then the peer re-adopts
    assert mgrs[0].handoff(1)
    assert mgrs[0].owned_slots() == {0}
    assert not store.get(LEASES, slot_lease_name(1)).holder_identity
    completed = metrics.shard_handoffs.samples().get(
        (("outcome", "completed"),), 0
    )
    assert completed >= 1
    mgrs[1].step()
    assert mgrs[1].owned_slots() == {1}
    assert caches[1].owned_slots == frozenset({1})
    # slot-1 backlog follows the owner: mgr-1's cache tracks its pods
    slot1_pods = [
        p for p in store.list(PODS) if p.name.startswith(tuple(picks[1]))
    ]
    assert all(caches[1]._has_task(p) for p in slot1_pods)
    assert all(not caches[0]._has_task(p) for p in slot1_pods)


def test_injected_handoff_failure_keeps_slot_and_backlog(tmp_path):
    store = ClusterStore()
    picks = gangs_for_slots(2, per_slot=1)
    seed_store(store, gangs=[picks[0][0], picks[1][0]], members=2)
    caches, mgrs = make_pair(store, tmp_path)
    store.release_lease(slot_lease_name(1), "mgr-1")
    mgrs[1]._set_owned(set())
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}
    faults.registry.arm("shard.handoff", count=1)
    before = metrics.shard_handoffs.samples().get((("outcome", "aborted"),), 0)
    assert not mgrs[0].handoff(1)
    assert metrics.shard_handoffs.samples().get(
        (("outcome", "aborted"),), 0
    ) == before + 1
    # the slot is kept whole: lease still held, owned set restored, the
    # backlog still tracked
    assert mgrs[0].owned_slots() == {0, 1}
    assert store.get(LEASES, slot_lease_name(1)).holder_identity == "mgr-0"
    assert caches[0].owned_slots == frozenset({0, 1})
    slot1_pods = [
        p for p in store.list(PODS) if p.name.startswith(picks[1][0])
    ]
    assert all(caches[0]._has_task(p) for p in slot1_pods)


def test_handoff_of_unowned_slot_is_refused(tmp_path):
    store = ClusterStore()
    seed_store(store)
    _, mgrs = make_pair(store, tmp_path)
    assert not mgrs[0].handoff(1)


# -- reclaim protocol ---------------------------------------------------------


def test_reclaim_request_hands_adopted_slot_back(tmp_path):
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0], picks[1][0]])
    caches, mgrs = make_pair(store, tmp_path)
    # shard 1 died; shard 0 adopted its slot
    store.release_lease(slot_lease_name(1), "mgr-1")
    mgrs[1]._set_owned(set())
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}
    # the reborn shard 1 requests its primary back (what start() does
    # when it finds the slot held by a survivor)
    store.try_acquire_lease(reclaim_lease_name(1), "mgr-1-reborn", 60.0)
    mgrs[0].step()  # _honor_reclaims -> graceful handoff
    assert mgrs[0].owned_slots() == {0}
    assert not store.get(LEASES, slot_lease_name(1)).holder_identity
    lease = store.try_acquire_lease(slot_lease_name(1), "mgr-1-reborn", 60.0)
    assert lease.holder_identity == "mgr-1-reborn"


def test_stale_reclaim_request_is_ignored(tmp_path):
    store = ClusterStore()
    seed_store(store)
    _, mgrs = make_pair(store, tmp_path)
    store.release_lease(slot_lease_name(1), "mgr-1")
    mgrs[1]._set_owned(set())
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}
    # an expired reclaim (the joiner died again) must not trigger a move
    store.try_acquire_lease(
        reclaim_lease_name(1), "mgr-1-reborn", 0.5, now=1.0
    )
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}


def test_start_and_stop_release_primary(tmp_path):
    store = ClusterStore()
    seed_store(store)
    cache = FederatedCache(store, shard=0, shards=2, shard_key="gang")
    mgr = ShardSlotManager(
        store, cache, identity="starter",
        lease_s=60.0, renew_s=30.0, adopt=False,
        journal_dir=str(tmp_path), grace_s=0.0, rebalance=0,
    )
    assert mgr.start(deadline_s=5.0)
    assert mgr.owned_slots() == {0}
    assert store.get(LEASES, slot_lease_name(0)).holder_identity == "starter"
    mgr.stop(release=True)
    assert not store.get(LEASES, slot_lease_name(0)).holder_identity


# -- streaming: adopted keys seed the trigger --------------------------------


def test_on_owned_slots_changed_seeds_and_prunes_stream_trigger(tmp_path):
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.streaming import StreamTrigger

    store = ClusterStore()
    seed_store(store)
    cache = SchedulerCache(store)
    sched = Scheduler(cache, schedule_period=1000.0)
    # periodic mode: no trigger, the call is a no-op
    sched.on_owned_slots_changed({"default/ga"}, {"default/gb"})
    trigger = StreamTrigger()
    sched._stream_trigger = trigger
    with trigger._lock:
        trigger._gangs.add("default/gb")
    sched.on_owned_slots_changed({"default/ga"}, {"default/gb"})
    with trigger._lock:
        backlog = set(trigger._gangs)
    assert backlog == {"default/ga"}  # adopted seeded, removed pruned
    assert trigger._event.is_set()


def test_handoff_parity_under_streaming_micro_cycles(tmp_path):
    """Graceful handoff while the receiving scheduler runs streaming
    micro-cycles: the adopted gang keys are seeded into the trigger, the
    next micro drain binds exactly the handed-off backlog, and the final
    world is exactly-once and fsck-clean."""
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.streaming import StreamState, StreamTrigger

    store = ClusterStore()
    picks = gangs_for_slots(2, per_slot=1)
    seed_store(store, nodes=2, gangs=[picks[1][0]], members=2)
    bind_counts: dict[str, int] = {}

    def on_update(old, new):
        if not old.node_name and new.node_name:
            key = f"{new.namespace}/{new.name}"
            bind_counts[key] = bind_counts.get(key, 0) + 1

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    caches, mgrs = make_pair(store, tmp_path)
    receiver = caches[1]  # slot-1's gang will be handed TO shard 1...
    # ...but first shard 0 adopted it (shard 1 restarted empty)
    store.release_lease(slot_lease_name(1), "mgr-1")
    mgrs[1]._set_owned(set())
    mgrs[0].step()
    assert mgrs[0].owned_slots() == {0, 1}
    assert not receiver._has_task(next(iter(store.list(PODS))))

    conf = tmp_path / "conf.yaml"
    conf.write_text(
        'actions: "enqueue, allocate, backfill"\n'
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "  - name: conformance\n"
        "- plugins:\n"
        "  - name: predicates\n"
        "  - name: nodeorder\n"
        "streaming: true\n"
    )
    sched = Scheduler(receiver, scheduler_conf=str(conf), schedule_period=1000.0)
    trigger = StreamTrigger()
    state = StreamState()
    sched._stream_trigger = trigger
    sched._stream_state = state
    trigger.attach()
    try:
        sched.run_once()  # adopt the resident table (nothing to bind yet)
        mgrs[1]._on_owned_change = (
            lambda adopted, removed: sched.on_owned_slots_changed(
                adopted, removed
            )
        )
        # the planned move: mgr-0 drains + releases, mgr-1 re-adopts —
        # the owned-change callback seeds the gang into the trigger
        assert mgrs[0].handoff(1)
        mgrs[1].step()
        assert mgrs[1].owned_slots() == {1}
        work = trigger.drain()
        assert f"default/{picks[1][0]}" in work.gangs
        sched.run_micro(work)
    finally:
        trigger.detach()
    placed = {
        f"{p.namespace}/{p.name}": p.node_name for p in store.list(PODS)
    }
    assert all(placed.values()), placed
    assert sorted(bind_counts.values()) == [1] * len(placed)
    assert fsck(store, shard_key="gang") == []


# -- journals -----------------------------------------------------------------


def test_adoption_reconciles_dead_shards_journal(tmp_path):
    """Orphaned intents in the dead owner's shard WAL are re-driven by
    the adopter BEFORE the backlog is rescheduled: the journaled
    placement lands exactly once even though the dead shard never
    dispatched it."""
    store = ClusterStore()
    picks = gangs_for_slots(2)
    seed_store(store, gangs=[picks[0][0], picks[1][0]], members=2)
    caches, mgrs = make_pair(store, tmp_path)
    # the dead shard journaled a gang's intents but never dispatched
    dead_slot = 0
    gang = picks[dead_slot][0]
    wal = WriteIntentJournal(shard_journal_path(str(tmp_path), dead_slot))
    entries = [
        (job_key("default", gang), f"default/{gang}-p{m}", "n0")
        for m in range(2)
    ]
    wal.append_intents("bind", entries, cycle=1, trace=None)
    wal.close()
    store.release_lease(slot_lease_name(dead_slot), "mgr-0")
    mgrs[0]._set_owned(set())
    mgrs[1].step()  # adoption runs reconcile_journal against the WAL
    assert dead_slot in mgrs[1].owned_slots()
    for m in range(2):
        pod = store.get_pod("default", f"{gang}-p{m}")
        assert pod.node_name == "n0", "journaled intent was not re-driven"
    orphans = WriteIntentJournal.replay(
        shard_journal_path(str(tmp_path), dead_slot)
    ).orphans
    assert orphans == []
    assert fsck(store, shard_key="gang") == []


# -- lease verbs over HTTP ----------------------------------------------------


@pytest.fixture()
def arbiter():
    from kube_batch_tpu.server import SchedulerServer

    srv = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    srv.start()
    try:
        yield srv
    finally:
        srv.stop()


def test_loopback_lease_verbs_round_trip(arbiter):
    """Slot leases work over the wire: a remote shard's try-acquire,
    renew-as-holder, steal-refused, release, and re-acquire all route
    through the arbiter's store."""
    from kube_batch_tpu.cache import LoopbackBackend

    backend = LoopbackBackend(f"http://127.0.0.1:{arbiter.listen_port}")
    name = slot_lease_name(0)
    lease = backend.try_acquire_lease(name, "remote-a", lease_duration=60.0)
    assert lease.holder_identity == "remote-a"
    # renewal by the holder keeps it; a live steal attempt is refused
    assert backend.try_acquire_lease(name, "remote-a", 60.0).holder_identity == "remote-a"
    assert backend.try_acquire_lease(name, "remote-b", 60.0).holder_identity == "remote-a"
    # the arbiter's own store agrees
    assert arbiter.store.get(LEASES, name).holder_identity == "remote-a"
    released = backend.release_lease(name, "remote-a")
    assert not released.holder_identity
    assert backend.try_acquire_lease(name, "remote-b", 60.0).holder_identity == "remote-b"


# -- metrics / observability --------------------------------------------------


def test_ownership_gauges_track_owned_set(tmp_path):
    store = ClusterStore()
    seed_store(store)
    _, mgrs = make_pair(store, tmp_path)
    mgrs[0]._publish_owned({0})
    assert metrics.shard_slots_owned.samples().get((), 0) == 1
    per_slot = metrics.shard_slot_owned.samples()
    assert per_slot.get((("slot", "0"),)) == 1.0
    assert per_slot.get((("slot", "1"),)) == 0.0
    mgrs[0]._publish_owned({0, 1})
    assert metrics.shard_slots_owned.samples().get((), 0) == 2
    assert metrics.shard_slot_owned.samples().get((("slot", "1"),)) == 1.0


def test_resharding_metrics_in_exposition():
    text = metrics.render_prometheus_text()
    for family in (
        "kube_batch_tpu_shard_slots_owned",
        "kube_batch_tpu_shard_slot_owned",
        "kube_batch_tpu_shard_adoptions_total",
        "kube_batch_tpu_shard_handoffs_total",
        "kube_batch_tpu_shard_takeover_seconds",
        "kube_batch_tpu_fleet_shard_up",
        "kube_batch_tpu_fleet_shard_last_scrape_age_seconds",
    ):
        assert family in text, family
