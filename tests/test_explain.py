"""Unschedulability forensics + placement provenance (PR 12 tentpole).

Covers the explain surface end to end: record parity serial ≡ XLA ≡
mesh {2,4,8} on the seeded per-plane world, the dominant-reason and
would-fit-if semantics, PodGroup condition enrichment, the journal
intent `explain` field, /debug/explain (server + registry), streaming
micro-cycles computing records for dirty gangs only, the federated
cross-shard aggregate over conditions matching a single-scheduler twin,
conf hot reload, and the zero-cost-off guarantee.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu import obs
from kube_batch_tpu.apis.types import POD_GROUP_UNSCHEDULABLE_TYPE
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.store import POD_GROUPS, PODS
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.obs import explain
from kube_batch_tpu.recovery import WriteIntentJournal
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.testing import (
    FakeCache,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

XLA_CONF = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""

SMOKE_TIERS_XLA = explain._SMOKE_TIERS.replace(
    'actions: "allocate"', 'actions: "xla_allocate"'
)


@pytest.fixture
def explaining(monkeypatch):
    """Explain on through the env var (the same switch the scheduler's
    conf-reload path re-resolves every cycle), registry cleared."""
    monkeypatch.setenv(explain.ENV, "1")
    explain.configure()
    explain.records.clear()
    yield
    explain.configure("off")
    explain.records.clear()


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


def run_smoke_world(action_name, mesh=None):
    """One session over the seeded per-plane world through the real
    action registry; returns (records, engaged mesh size)."""
    tiers_yaml = explain._SMOKE_TIERS if action_name == "allocate" else SMOKE_TIERS_XLA
    cache = FakeCache(explain._smoke_world())
    args = {"xla_allocate": {"mesh": mesh}} if mesh else {}
    ssn = open_session(cache, parse_scheduler_conf(tiers_yaml).tiers, args)
    action = get_action(action_name)
    try:
        action.execute(ssn)
        jobs = dict(ssn.jobs)
    finally:
        close_session(ssn)
    recs = dict(getattr(ssn, "explain_records", {}) or {})
    return recs, getattr(action, "last_mesh_size", 1), jobs


def canon(recs):
    return json.dumps(recs, sort_keys=True)


# -- parity: serial = XLA = mesh ----------------------------------------------


def test_explain_parity_serial_xla_mesh(explaining):
    """The tentpole acceptance: records from the serial action's
    task-by-task twin, the single-chip batched kernel, and the sharded
    mesh kernel at 2/4/8 devices are byte-identical — explain parity is
    pinned exactly like placement parity."""
    serial, _, _ = run_smoke_world("allocate")
    xla, mesh1, _ = run_smoke_world("xla_allocate")
    assert mesh1 == 1
    assert serial and canon(serial) == canon(xla)
    for n in (2, 4, 8):
        sharded, mesh_n, _ = run_smoke_world("xla_allocate", mesh=f"cpu:{n}")
        assert mesh_n == n, f"mesh cpu:{n} did not engage"
        assert canon(sharded) == canon(xla)


def test_designed_reasons_and_would_fit_if(explaining):
    """Each seeded gang reports its designed dominant plane, with the
    would-fit-if analysis flagging that plane as the single fix, and
    the bound gang gets a light provenance record."""
    recs, _, _ = run_smoke_world("xla_allocate")
    expected = {
        "default/g-static": "static",
        "default/g-resources": "resources",
        "default/g-ports": "ports",
        "default/g-room": "room",
    }
    for uid, plane in expected.items():
        rec = recs[uid]
        assert rec["verdict"] == "unschedulable"
        assert rec["reason"] == plane
        assert rec["feasible"] == 0
        assert rec["would_fit_if"][plane], f"{uid}: {plane} not a would-fit fix"
        assert rec["eliminated"][plane] > 0
        assert rec["near_miss"], f"{uid}: no near-miss nodes"
        for nm in rec["near_miss"]:
            assert set(nm["planes"]) == set(explain.PLANES)
    bound = recs["default/g-bound"]
    assert bound["verdict"] == "bound" and bound["reason"] == "bound"
    assert bound["ready"] >= bound["min"]


def test_ports_gang_reads_ports_not_static(explaining):
    """The cheapest-single-fix rule: g-ports is zone-confined (8 nodes
    statically eliminated) AND port-blocked (2 nodes) — the dominant
    reason must be the plane whose solo relaxation actually frees a
    node, not the biggest eliminator."""
    recs, _, _ = run_smoke_world("xla_allocate")
    rec = recs["default/g-ports"]
    assert rec["eliminated"]["static"] > rec["eliminated"]["ports"]
    # BOTH are single fixes (relaxing static frees other-zone nodes
    # whose port is unclaimed; releasing the port frees zone-c) — the
    # dominant reason is the cheaper of the two, by eliminated count
    assert rec["would_fit_if"]["static"] and rec["would_fit_if"]["ports"]
    assert rec["reason"] == "ports"


# -- conditions ---------------------------------------------------------------


def test_conditions_carry_reason_and_forensics_message(explaining):
    """The gang plugin swaps its generic Unschedulable reason for the
    explain record's dominant plane at session close, with the dense
    one-line forensics message."""
    _, _, jobs = run_smoke_world("xla_allocate")
    for uid, plane in (("default/g-ports", "ports"), ("default/g-room", "room")):
        conds = jobs[uid].pod_group.status.conditions
        assert conds, f"{uid}: no condition written"
        last = conds[-1]
        assert last.type == POD_GROUP_UNSCHEDULABLE_TYPE
        assert last.reason == plane
        assert "nodes feasible" in last.message
        assert plane in last.message.split("would fit if: ")[1]
    # the bound gang must NOT carry an explain-flavored Unschedulable
    bound_conds = jobs["default/g-bound"].pod_group.status.conditions
    assert all(
        c.reason not in explain.PLANES for c in bound_conds
    )


# -- off path -----------------------------------------------------------------


def test_off_cycle_records_nothing(tmp_path):
    assert not explain.enabled()
    recs, _, jobs = run_smoke_world("xla_allocate")
    assert recs == {}
    assert explain.records.snapshot() == []
    # conditions fall back to the generic gang-plugin reason
    for uid in ("default/g-ports", "default/g-room"):
        conds = jobs[uid].pod_group.status.conditions
        assert conds and conds[-1].reason not in explain.PLANES


def test_off_overhead_is_one_branch():
    """With explain off, the action-side gate is a module bool check —
    guard a generous per-call bound so an accidental allocation or
    registry touch on the off path fails loudly."""
    assert not explain.enabled()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        if explain.enabled():  # pragma: no cover - off in this test
            explain.explain_session(None)
    off_cost = (time.perf_counter() - t0) / n
    assert off_cost < 5e-5


# -- scheduler integration: journal, debug endpoint, hot reload ---------------


def seed_store(store, stuck=True):
    store.create_queue(build_queue("default"))
    for i in range(4):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=32))
        )
    store.create_pod_group(build_pod_group("g-fit", min_member=3))
    for m in range(3):
        store.create_pod(build_pod(
            name=f"g-fit-p{m}", group_name="g-fit",
            req=build_resource_list(cpu=1, memory="512Mi"),
        ))
    if stuck:
        store.create_pod_group(build_pod_group("g-stuck", min_member=1))
        store.create_pod(build_pod(
            name="g-stuck-p0", group_name="g-stuck",
            req=build_resource_list(cpu=999, memory="512Mi"),
        ))


def make_scheduler(store, tmp_path, conf=XLA_CONF, journal=None, period=0.05):
    path = tmp_path / "conf.yaml"
    path.write_text(conf)
    cache = SchedulerCache(store, journal=journal)
    return cache, Scheduler(cache, scheduler_conf=str(path), schedule_period=period)


def test_journal_intents_carry_explain_field(tmp_path, explaining):
    """Bind intents written during the cycle's dispatch carry the
    compact explain payload (verdict/reason/ready/min); replay ignores
    the extra key, so the WAL doubles as labeled decision tuples."""
    store = ClusterStore()
    seed_store(store)
    jpath = tmp_path / "j.wal"
    _, sched = make_scheduler(store, tmp_path, journal=WriteIntentJournal(str(jpath)))
    sched.run_once()
    assert sched.cache.binder  # the cycle ran
    lines = [json.loads(ln) for ln in jpath.read_text().splitlines()]
    intents = [r for r in lines if r.get("rec") == "intent"]
    assert intents, "no bind intents journaled"
    tagged = [r for r in intents if "explain" in r]
    assert tagged, "no intent carried an explain payload"
    for r in tagged:
        assert r["explain"]["verdict"] == "bound"
        assert r["explain"]["reason"] == "bound"
        assert r["explain"]["ready"] >= r["explain"]["min"]
    # the stuck gang never dispatched, so its record lives in the
    # registry (and /debug/explain), not the WAL
    stuck = explain.records.get("default/g-stuck")
    assert stuck is not None and stuck["verdict"] == "unschedulable"
    assert stuck["reason"] == "resources"


def test_debug_explain_endpoint(tmp_path, explaining):
    from kube_batch_tpu.server import SchedulerServer

    store = ClusterStore()
    seed_store(store)
    _, sched = make_scheduler(store, tmp_path)
    sched.run_once()
    server = SchedulerServer(
        scheduler_name="explain-test", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()
    try:
        def get(path):
            url = f"http://127.0.0.1:{server.listen_port}{path}"
            with urllib.request.urlopen(url, timeout=5) as r:
                return r.status, json.loads(r.read().decode())

        status, payload = get("/debug/explain")
        assert status == 200 and payload["enabled"]
        names = {r["name"] for r in payload["records"]}
        assert {"default/g-fit", "default/g-stuck"} <= names
        assert payload["aggregate"]["unschedulable"] >= 1
        assert payload["aggregate"]["reasons"].get("resources", 0) >= 1

        status, one = get("/debug/explain?gang=default/g-stuck")
        assert status == 200 and len(one["records"]) == 1
        assert one["records"][0]["reason"] == "resources"
        assert one["records"][0]["would_fit_if"]["resources"]
    finally:
        server.stop()


def test_conf_explain_key_hot_reloads_the_switch(tmp_path):
    store = ClusterStore()
    seed_store(store, stuck=False)
    conf = tmp_path / "conf.yaml"
    conf.write_text(XLA_CONF + 'explain: "on"\n')
    cache = SchedulerCache(store)
    sched = Scheduler(cache, scheduler_conf=str(conf), schedule_period=0.05)
    try:
        sched._load_conf()
        assert explain.enabled()
        conf.write_text(XLA_CONF + 'explain: "off"\n')
        sched._load_conf()
        assert not explain.enabled()
    finally:
        explain.configure("off")


# -- streaming: dirty gangs only ----------------------------------------------

STREAM_CONF = XLA_CONF + "streaming: true\n"


def test_micro_cycle_explains_dirty_gangs_only(tmp_path, explaining):
    """A micro-cycle's session world holds only the dirty gangs, so its
    explain pass records exactly those — earlier full-cycle records for
    untouched gangs stay in the registry, and the micro record matches
    what a full-cycle twin computes for the same gang (parity)."""
    store = ClusterStore()
    seed_store(store)  # g-fit binds, g-stuck stays unschedulable
    _, sched = make_scheduler(store, tmp_path, conf=STREAM_CONF, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        # _stream_state appears before the first full cycle completes,
        # so wait for the cycle's explain publish, not just the state
        wait_until(lambda: explain.records.get("default/g-stuck") is not None,
                   what="full-cycle explain record for g-stuck")
        stuck_before = explain.records.get("default/g-stuck")
        assert stuck_before["reason"] == "resources"
        micro_before = sched.micro_cycles_run
        store.create_pod_group(build_pod_group("g-new", min_member=2))
        for m in range(2):
            store.create_pod(build_pod(
                name=f"g-new-p{m}", group_name="g-new",
                req=build_resource_list(cpu=1, memory="512Mi"),
            ))
        wait_until(
            lambda: sum(1 for p in store.list(PODS) if p.node_name) >= 5,
            what="micro-cycle binds for g-new",
        )
    finally:
        stop.set()
        t.join(timeout=10.0)
    if sched.micro_cycles_run > micro_before:
        # the arrival was served by a micro-cycle: its explain span saw
        # only the dirty gang, not the resident stuck gang
        micro_spans = [
            s for s in obs.recorder.spans() if s["name"] == "explain"
            and s["attrs"].get("micro")
        ] if obs.enabled() else []
        for s in micro_spans:
            assert s["attrs"]["gangs"] <= 1
    new_rec = explain.records.get("default/g-new")
    assert new_rec is not None and new_rec["verdict"] == "bound"
    # the stuck gang's full-cycle record survived the micro-cycle
    stuck = explain.records.get("default/g-stuck")
    assert stuck is not None and stuck["reason"] == "resources"
    # parity with a full-cycle twin over an identically-seeded world
    twin_store = ClusterStore()
    seed_store(twin_store)
    twin_store.create_pod_group(build_pod_group("g-new", min_member=2))
    for m in range(2):
        twin_store.create_pod(build_pod(
            name=f"g-new-p{m}", group_name="g-new",
            req=build_resource_list(cpu=1, memory="512Mi"),
        ))
    explain.records.clear()
    _, twin = make_scheduler(twin_store, tmp_path)
    twin.run_once()
    twin_rec = explain.records.get("default/g-new")
    assert twin_rec is not None
    assert {k: new_rec[k] for k in ("verdict", "reason", "min")} == \
        {k: twin_rec[k] for k in ("verdict", "reason", "min")}


# -- federation: shard-local reasons + cross-shard aggregate ------------------


def _seed_federated(store):
    """Two gangs that shard apart under shard_key=gang: one binds, one
    is resource-stuck — each shard computes its own explain records."""
    seed_store(store)


def test_federated_shards_aggregate_matches_single_twin(tmp_path, explaining):
    """Each shard's scheduler computes explain records for its own
    gangs (shard-local reasons), the reasons ride PodGroup conditions
    into the shared store, and aggregate_conditions over store truth
    equals the aggregate a single-scheduler twin produces."""
    from kube_batch_tpu.federation import FederatedCache

    store = ClusterStore()
    _seed_federated(store)
    shard_records = {}
    for shard in range(2):
        explain.records.clear()
        path = tmp_path / f"conf-{shard}.yaml"
        path.write_text(XLA_CONF)
        cache = FederatedCache(store, shard=shard, shards=2, shard_key="gang")
        sched = Scheduler(cache, scheduler_conf=str(path), schedule_period=0.05)
        sched.run_once()
        shard_records[shard] = {
            r["name"]: r for r in explain.records.snapshot()
        }
    # shard-local: the two shards saw disjoint gang sets, union = all
    names = [set(r) for r in shard_records.values()]
    assert names[0].isdisjoint(names[1])
    assert names[0] | names[1] == {"default/g-fit", "default/g-stuck"}
    stuck_shard = 0 if "default/g-stuck" in names[0] else 1
    assert shard_records[stuck_shard]["default/g-stuck"]["reason"] == "resources"
    # cross-shard aggregate over store-truth conditions
    agg = explain.aggregate_conditions(store.list(POD_GROUPS))
    assert agg["unschedulable"] == 1
    assert agg["reasons"] == {"resources": 1}
    # equals the single-scheduler twin's aggregate over ITS store
    twin_store = ClusterStore()
    _seed_federated(twin_store)
    explain.records.clear()
    _, twin = make_scheduler(twin_store, tmp_path)
    twin.run_once()
    twin_agg = explain.aggregate_conditions(twin_store.list(POD_GROUPS))
    assert agg == twin_agg


# -- registry bounds ----------------------------------------------------------


def test_registry_is_bounded_and_lru():
    reg = explain._Registry(max_records=3)
    for i in range(5):
        reg.update({f"g{i}": {"gang": f"g{i}"}})
    assert len(reg.snapshot()) == 3
    assert reg.get("g0") is None and reg.get("g4") is not None
    reg.update({"g2": {"gang": "g2", "touched": True}})  # moves to back
    reg.update({"g5": {"gang": "g5"}})
    assert reg.get("g2") is not None  # re-publish refreshed recency
    reg.clear()
    assert reg.snapshot() == []
