"""NodeInfo accounting invariants (reference pkg/scheduler/api/node_info_test.go)."""

import pytest

from kube_batch_tpu.api import NodeInfo, Resource, TaskStatus
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.testing import build_node, build_resource_list, build_task


def rl(cpu, mem):
    return build_resource_list(cpu, mem)


def make_node(cpu="8", mem="8G"):
    return NodeInfo(build_node("n1", rl(cpu, mem)))


class TestAddRemove:
    def test_add_task_consumes_idle(self):
        """reference node_info_test.go TestNodeInfo_AddPod."""
        ni = make_node()
        ni.add_task(build_task(name="p1", req=rl("1", "1G"), node_name="n1",
                               phase=PodPhase.RUNNING))
        ni.add_task(build_task(name="p2", req=rl("2", "2G"), node_name="n1",
                               phase=PodPhase.RUNNING))
        assert ni.idle == Resource.from_resource_list(rl("5", "5G"))
        assert ni.used == Resource.from_resource_list(rl("3", "3G"))
        assert len(ni.tasks) == 2

    def test_remove_task_restores_idle(self):
        """reference node_info_test.go TestNodeInfo_RemovePod."""
        ni = make_node()
        t1 = build_task(name="p1", req=rl("1", "1G"), node_name="n1", phase=PodPhase.RUNNING)
        t2 = build_task(name="p2", req=rl("2", "2G"), node_name="n1", phase=PodPhase.RUNNING)
        ni.add_task(t1)
        ni.add_task(t2)
        ni.remove_task(t1)
        assert ni.idle == Resource.from_resource_list(rl("6", "6G"))
        assert ni.used == Resource.from_resource_list(rl("2", "2G"))

    def test_add_duplicate_raises(self):
        ni = make_node()
        t = build_task(name="p1", req=rl("1", "1G"), node_name="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        with pytest.raises(KeyError):
            ni.add_task(t)

    def test_remove_missing_raises(self):
        with pytest.raises(KeyError):
            make_node().remove_task(build_task(name="ghost", node_name="n1"))


class TestStatusAccounting:
    def test_releasing_task(self):
        """Releasing consumes idle AND is tracked in releasing
        (node_info.go:120-123)."""
        ni = make_node()
        t = build_task(name="p1", req=rl("2", "2G"), node_name="n1", phase=PodPhase.RUNNING)
        t.status = TaskStatus.RELEASING
        ni.add_task(t)
        assert ni.idle == Resource.from_resource_list(rl("6", "6G"))
        assert ni.releasing == Resource.from_resource_list(rl("2", "2G"))
        assert ni.used == Resource.from_resource_list(rl("2", "2G"))
        ni.remove_task(t)
        assert ni.idle == Resource.from_resource_list(rl("8", "8G"))
        assert ni.releasing.is_empty()

    def test_pipelined_task_rides_releasing(self):
        """Pipelined subtracts from releasing, not idle (node_info.go:124-125)."""
        ni = make_node()
        rel = build_task(name="victim", req=rl("2", "2G"), node_name="n1",
                         phase=PodPhase.RUNNING)
        rel.status = TaskStatus.RELEASING
        ni.add_task(rel)
        pipe = build_task(name="incoming", req=rl("2", "2G"), node_name="n1")
        pipe.status = TaskStatus.PIPELINED
        ni.add_task(pipe)
        assert ni.releasing.is_empty()  # 2G releasing - 2G pipelined
        assert ni.idle == Resource.from_resource_list(rl("6", "6G"))

    def test_task_clone_isolation(self):
        """Node holds a clone: caller status flips don't corrupt accounting
        (node_info.go:117)."""
        ni = make_node()
        t = build_task(name="p1", req=rl("1", "1G"), node_name="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        t.status = TaskStatus.RELEASING  # mutate caller's copy
        ni.remove_task(t)  # looked up by key; node's clone still RUNNING
        assert ni.idle == Resource.from_resource_list(rl("8", "8G"))
        assert ni.releasing.is_empty()


class TestSetNodeClone:
    def test_set_node_recomputes(self):
        """reference node_info_test.go TestNodeInfo_SetNode."""
        ni = make_node("4", "4G")
        ni.add_task(build_task(name="p1", req=rl("1", "1G"), node_name="n1",
                               phase=PodPhase.RUNNING))
        bigger = build_node("n1", rl("16", "16G"))
        ni.set_node(bigger)
        assert ni.allocatable == Resource.from_resource_list(rl("16", "16G"))
        assert ni.idle == Resource.from_resource_list(rl("15", "15G"))
        assert ni.used == Resource.from_resource_list(rl("1", "1G"))

    def test_clone(self):
        ni = make_node()
        ni.add_task(build_task(name="p1", req=rl("1", "1G"), node_name="n1",
                               phase=PodPhase.RUNNING))
        c = ni.clone()
        assert c.idle == ni.idle and c.used == ni.used and len(c.tasks) == 1
        c.add_task(build_task(name="p2", req=rl("1", "1G"), node_name="n1",
                              phase=PodPhase.RUNNING))
        assert len(ni.tasks) == 1  # original untouched
