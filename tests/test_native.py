"""Parity tests for the native (C++) hot loops vs their Python twins.

The native module (kube_batch_tpu/native/_hotloops.cpp) reimplements
the replay path's per-event session surgery; these tests pin its
semantics to the pure-Python loop it replaces: identical status
flips, node_name sets, residency-clone sharing rules
(api/job_info.py clone_for_residency), status-index dict contents,
and the mutation-free volume-guard prepass. Skipped wholesale when
the toolchain cannot build the module (the framework then runs the
Python loops — same results, slower)."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.native import lib
from kube_batch_tpu.testing import build_task

pytestmark = pytest.mark.skipif(lib is None, reason="native module unavailable")


def _mk_tasks(n):
    return [
        build_task(namespace="ns", name=f"p{i}", req={"cpu": 1.0}) for i in range(n)
    ]


def _run_python_twin(tasks, tkeys, node_tasks, node_names, rows, nrows, allocs, counts):
    """The exact loop _Replayer._assign_segments_py runs for volume-less
    rows (volume rows never reach bulk_assign — the guard test below)."""
    segments = []
    pos = 0
    for cnt in counts:
        alloc_d, pipe_d = {}, {}
        for i in range(pos, pos + cnt):
            task = tasks[rows[i]]
            if allocs[i]:
                task.volume_ready = True
                task.status = TaskStatus.ALLOCATED
                alloc_d[task.uid] = task
            else:
                task.status = TaskStatus.PIPELINED
                pipe_d[task.uid] = task
            task.node_name = node_names[nrows[i]]
            node_tasks[nrows[i]][tkeys[rows[i]]] = task.clone_for_residency()
        pos += cnt
        segments.append((alloc_d, pipe_d))
    return segments


class TestBulkAssign:
    def test_matches_python_twin(self):
        rng = np.random.default_rng(7)
        n, n_nodes = 200, 7
        rows = rng.permutation(n).tolist()
        nrows = rng.integers(0, n_nodes, n).tolist()
        allocs = rng.integers(0, 2, n).astype(np.uint8)
        counts = [50, 100, 0, 50]

        tasks_a, tasks_b = _mk_tasks(n), _mk_tasks(n)
        tkeys = [f"{t.namespace}/{t.name}" for t in tasks_a]
        nt_a = [dict() for _ in range(n_nodes)]
        nt_b = [dict() for _ in range(n_nodes)]
        nn = [f"node-{i}" for i in range(n_nodes)]

        seg_n = lib.bulk_assign(
            tasks_a, tkeys, nt_a, nn, rows, nrows, allocs.tobytes(), counts,
            TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
        )
        seg_p = _run_python_twin(
            tasks_b, tkeys, nt_b, nn, rows, nrows, allocs.tolist(), counts
        )

        assert len(seg_n) == len(seg_p) == 4
        for (an, pn), (ap, pp) in zip(seg_n, seg_p):
            assert list(an) == list(ap)  # same uids, same insertion order
            assert list(pn) == list(pp)
        for ta, tb in zip(tasks_a, tasks_b):
            assert ta.status is tb.status
            assert ta.node_name == tb.node_name
            assert ta.volume_ready == tb.volume_ready
        for da, db in zip(nt_a, nt_b):
            assert list(da) == list(db)
            for k in da:
                ca, cb = da[k], db[k]
                assert ca.status is cb.status and ca.node_name == cb.node_name

    def test_clone_shares_resources_and_detaches_status(self):
        tasks = _mk_tasks(1)
        nt = [dict()]
        lib.bulk_assign(
            tasks, ["ns/p0"], nt, ["n0"], [0], [0], bytes([1]), [1],
            TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
        )
        clone = nt[0]["ns/p0"]
        t = tasks[0]
        assert clone is not t
        assert clone.resreq is t.resreq and clone.init_resreq is t.init_resreq
        assert clone.pod is t.pod and clone.uid == t.uid
        assert clone.status is TaskStatus.ALLOCATED
        t.status = TaskStatus.BINDING  # later dispatch flip
        assert clone.status is TaskStatus.ALLOCATED  # resident unaffected

    def test_volume_rows_raise_without_mutation(self):
        tasks = _mk_tasks(2)
        tasks[1].pod.volumes = ["claim-1"]
        before = [(t.status, t.node_name, t.volume_ready) for t in tasks]
        nt = [dict()]
        with pytest.raises(ValueError, match="volume"):
            lib.bulk_assign(
                tasks, ["ns/p0", "ns/p1"], nt, ["n0"], [0, 1], [0, 0],
                bytes([1, 1]), [2], TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
            )
        # prepass fired before any event applied: nothing changed
        assert [(t.status, t.node_name, t.volume_ready) for t in tasks] == before
        assert not nt[0]

    def test_pipelined_rows_skip_volume_guard(self):
        # only Allocated events bind volumes; a Pipelined volume row is fine
        tasks = _mk_tasks(1)
        tasks[0].pod.volumes = ["claim-1"]
        nt = [dict()]
        lib.bulk_assign(
            tasks, ["ns/p0"], nt, ["n0"], [0], [0], bytes([0]), [1],
            TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
        )
        assert tasks[0].status is TaskStatus.PIPELINED
        assert not tasks[0].volume_ready

    def test_clone_survives_collection(self):
        import gc

        tasks = _mk_tasks(3)
        nt = [dict()]
        lib.bulk_assign(
            tasks, [f"ns/p{i}" for i in range(3)], nt, ["n0"], [0, 1, 2],
            [0, 0, 0], bytes([1, 1, 1]), [3],
            TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
        )
        clones = dict(nt[0])
        del tasks, nt
        gc.collect()  # clones are GC-untracked; refcounting must keep them
        for k, c in clones.items():
            assert c.uid and c.status is TaskStatus.ALLOCATED

    def test_length_mismatch_rejected(self):
        tasks = _mk_tasks(1)
        with pytest.raises(ValueError):
            lib.bulk_assign(
                tasks, ["ns/p0"], [{}], ["n0"], [0, 0], [0], bytes([1]), [1],
                TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
            )
        with pytest.raises(IndexError):
            lib.bulk_assign(
                tasks, ["ns/p0"], [{}], ["n0"], [5], [0], bytes([1]), [1],
                TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
            )


class TestCollectPending:
    def _python_twin(self, jobs):
        from kube_batch_tpu.api.types import TaskStatus

        out = []
        for job in jobs:
            pending = [
                t
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty()
            ]
            pending.sort(
                key=lambda t: (-t.priority, t.pod.metadata.creation_timestamp, t.uid)
            )
            out.append(pending)
        return out

    def _jobs(self):
        import random

        from kube_batch_tpu.api.job_info import JobInfo
        from kube_batch_tpu.api.types import TaskStatus

        rng = random.Random(11)
        jobs = []
        for j in range(6):
            job = JobInfo(uid=f"job-{j}")
            for i in range(rng.randint(0, 12)):
                t = build_task(
                    namespace="ns",
                    name=f"j{j}t{i}",
                    req=rng.choice([{"cpu": 1.0}, {"cpu": 0.5}, None]),
                    priority=rng.choice([None, 1, 5, 9]),
                )
                t.pod.metadata.creation_timestamp = rng.choice([100.0, 200.0, 300.0])
                if rng.random() < 0.3:
                    t.pod.node_selector["zone"] = "a"
                if rng.random() < 0.2:
                    t.pod.containers[0].ports = [8080]
                job.add_task_info(t)
            jobs.append(job)
        return jobs

    def test_matches_python_extraction(self):
        from kube_batch_tpu.api.resource_info import (
            MIN_MEMORY,
            MIN_MILLI_CPU,
            MIN_MILLI_SCALAR,
        )
        from kube_batch_tpu.api.types import TaskStatus

        jobs = self._jobs()
        native = lib.collect_pending(
            jobs, TaskStatus.PENDING, MIN_MILLI_CPU, MIN_MEMORY, MIN_MILLI_SCALAR
        )
        python = self._python_twin(jobs)
        assert len(native) == len(python)
        for (n_tasks, flags), p_tasks in zip(native, python):
            assert [t.uid for t in n_tasks] == [t.uid for t in p_tasks]
            for t, fl in zip(n_tasks, flags):
                plain = (
                    not t.pod.node_selector
                    and t.pod.affinity is None
                    and not t.pod.tolerations
                    and not t.pod.volumes
                    and len(t.pod.containers) == 1
                    and not t.pod.containers[0].ports
                )
                assert bool(fl) == plain, t.uid

    def test_empty_resreq_excluded(self):
        from kube_batch_tpu.api.job_info import JobInfo
        from kube_batch_tpu.api.resource_info import (
            MIN_MEMORY,
            MIN_MILLI_CPU,
            MIN_MILLI_SCALAR,
        )
        from kube_batch_tpu.api.types import TaskStatus

        job = JobInfo(uid="j")
        job.add_task_info(build_task(namespace="ns", name="be", req=None))
        job.add_task_info(build_task(namespace="ns", name="real", req={"cpu": 1.0}))
        (tasks, flags), = lib.collect_pending(
            [job], TaskStatus.PENDING, MIN_MILLI_CPU, MIN_MEMORY, MIN_MILLI_SCALAR
        )
        assert [t.name for t in tasks] == ["real"]

    def test_encode_with_and_without_native_agree(self, monkeypatch):
        import numpy as np

        import kube_batch_tpu.ops.encode as E
        from kube_batch_tpu.models import multi_tenant_ml
        from kube_batch_tpu.testing import FakeCache

        # one world, snapshotted per encode: the clusters must be equal
        # to the timestamp (task_created rides the arrays now)
        fc = FakeCache(multi_tenant_ml())

        def enc():
            cluster = fc.snapshot()
            return E.encode_session(cluster.jobs, cluster.nodes, cluster.queues)

        a = enc()
        monkeypatch.setattr(E, "_native", None)
        b = enc()
        assert [t.uid for t in a.tasks] == [t.uid for t in b.tasks]
        for k in a.arrays:
            np.testing.assert_array_equal(
                np.asarray(a.arrays[k]), np.asarray(b.arrays[k]), err_msg=k
            )


class TestHalfInitializedGuards:
    """r4 advisor findings: unset __slots__ members must surface as
    AttributeError (with the exception actually set), never a segfault
    or a bare SystemError; counts inconsistencies must raise before any
    mutation."""

    def test_bulk_assign_counts_mismatch_raises_premutation(self):
        tasks = _mk_tasks(3)
        before = [(t.status, t.node_name, t.volume_ready) for t in tasks]
        nt = [dict()]
        for bad_counts in ([2], [4], [2, 2]):  # under / over / over-split
            with pytest.raises(ValueError, match="count"):
                lib.bulk_assign(
                    tasks, [f"ns/p{i}" for i in range(3)], nt, ["n0"],
                    [0, 1, 2], [0, 0, 0], bytes([1, 1, 1]), bad_counts,
                    TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
                )
            assert [(t.status, t.node_name, t.volume_ready) for t in tasks] == before
            assert not nt[0]

    def test_bulk_assign_null_pod_slot_raises(self):
        tasks = _mk_tasks(2)
        del tasks[1].pod  # unset the slot: C-level member is now NULL
        nt = [dict()]
        with pytest.raises(AttributeError, match="pod"):
            lib.bulk_assign(
                tasks, ["ns/p0", "ns/p1"], nt, ["n0"], [0, 1], [0, 0],
                bytes([1, 1]), [2], TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
            )
        assert not nt[0]  # prepass: nothing mutated

    def test_bulk_assign_null_uid_slot_raises(self):
        tasks = _mk_tasks(2)
        del tasks[0].uid  # would be a NULL dict key in the mutation loop
        nt = [dict()]
        with pytest.raises(AttributeError, match="uid"):
            lib.bulk_assign(
                tasks, ["ns/p0", "ns/p1"], nt, ["n0"], [0, 1], [0, 0],
                bytes([0, 0]), [2], TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
            )
        assert not nt[0]
        assert tasks[1].status is not TaskStatus.PIPELINED  # prepass: no mutation

    def test_collect_pending_null_pod_slot_raises(self):
        from kube_batch_tpu.api.job_info import JobInfo
        from kube_batch_tpu.api.resource_info import (
            MIN_MEMORY,
            MIN_MILLI_CPU,
            MIN_MILLI_SCALAR,
        )

        job = JobInfo(uid="j")
        t = build_task(namespace="ns", name="ghost", req={"cpu": 1.0})
        job.add_task_info(t)
        del t.pod
        with pytest.raises(AttributeError):
            lib.collect_pending(
                [job], TaskStatus.PENDING, MIN_MILLI_CPU, MIN_MEMORY,
                MIN_MILLI_SCALAR,
            )

    def test_extract_task_columns_null_scalars_slot_raises(self):
        t = build_task(namespace="ns", name="t0", req={"cpu": 1.0})
        t.job = "j"
        del t.resreq.scalars
        req = np.zeros((1, 2), np.float32)
        res = np.zeros((1, 2), np.float32)
        job_out = np.zeros(1, np.int32)
        hs = np.zeros(1, np.uint8)
        rhs = np.zeros(1, np.uint8)
        with pytest.raises(AttributeError, match="scalars"):
            lib.extract_task_columns([t], {"j": 0}, req, res, job_out, hs, rhs)


class TestBulkSetSlot:
    def test_sets_every_object(self):
        tasks = _mk_tasks(50)
        lib.bulk_set_slot(tasks, "status", TaskStatus.BINDING)
        assert all(t.status is TaskStatus.BINDING for t in tasks)

    def test_non_slot_attr_rejected(self):
        with pytest.raises(AttributeError):
            lib.bulk_set_slot(_mk_tasks(1), "not_a_slot", 1)
        with pytest.raises(TypeError):
            # exists on the type but is a method, not a member slot
            lib.bulk_set_slot(_mk_tasks(1), "clone", 1)

    def test_empty_list_ok(self):
        lib.bulk_set_slot([], "status", TaskStatus.BINDING)


class TestHistogramNdarrayPath:
    def test_matches_scalar_observe(self):
        from kube_batch_tpu.metrics import Histogram

        buckets = [0.1, 1.0, 10.0]
        h1, h2 = Histogram("a", "", buckets), Histogram("b", "", buckets)
        vals = [0.05, 0.1, 0.5, 1.0, 5.0, 50.0]
        for v in vals:
            h1.observe(v)
        h2.observe_many(np.asarray(vals))
        assert h1.snapshot() == h2.snapshot()


class TestActionUsesNative:
    def test_xla_allocate_with_and_without_native_agree(self, monkeypatch):
        """The full action, native path vs forced-Python path, must
        produce identical binds and session state on a gang cluster."""
        import kube_batch_tpu.actions.xla_allocate as XA
        from kube_batch_tpu.conf import parse_scheduler_conf
        from kube_batch_tpu.framework import close_session, get_action, open_session
        from kube_batch_tpu.models import synthetic
        from kube_batch_tpu.testing import FakeCache
        from bench import TIERS_YAML

        def run():
            cache = FakeCache(synthetic(120, 16))
            ssn = open_session(cache, parse_scheduler_conf(TIERS_YAML).tiers)
            get_action("xla_allocate").execute(ssn)
            binds = dict(cache.binder.binds)
            close_session(ssn)
            return binds

        native_binds = run()
        monkeypatch.setattr(XA, "_native", None)
        python_binds = run()
        assert native_binds == python_binds and len(native_binds) > 0


class TestR5PrepassContracts:
    """r5 native additions: every mutating entry point must fail
    PRE-mutation so the Python fallbacks never double-apply."""

    def test_bulk_dispatch_bad_index_raises_premutation(self):
        from kube_batch_tpu.api.job_info import JobInfo

        good = JobInfo(uid="g")
        t = build_task(namespace="ns", name="t0", req={"cpu": 1.0})
        good.add_task_info(t)
        good.update_task_status(t, TaskStatus.ALLOCATED)

        class Weird:
            task_status_index = "not-a-dict"

        with pytest.raises(TypeError, match="task_status_index"):
            lib.bulk_dispatch(
                [good, Weird()], bytes([1, 1]),
                TaskStatus.ALLOCATED, TaskStatus.BINDING,
            )
        # prepass fired before any bucket moved
        assert TaskStatus.ALLOCATED in good.task_status_index
        assert TaskStatus.BINDING not in good.task_status_index

    def test_bulk_dispatch_moves_buckets_and_returns_tasks(self):
        from kube_batch_tpu.api.job_info import JobInfo

        jobs = []
        for j in range(3):
            job = JobInfo(uid=f"j{j}")
            for i in range(4):
                t = build_task(namespace="ns", name=f"j{j}t{i}", req={"cpu": 1.0})
                job.add_task_info(t)
                job.update_task_status(t, TaskStatus.ALLOCATED)
            jobs.append(job)
        out = lib.bulk_dispatch(
            jobs, bytes([1, 0, 1]), TaskStatus.ALLOCATED, TaskStatus.BINDING
        )
        assert [t.name for t in out] == [
            f"j{j}t{i}" for j in (0, 2) for i in range(4)
        ]
        for j, job in enumerate(jobs):
            if j == 1:
                assert TaskStatus.ALLOCATED in job.task_status_index
            else:
                assert TaskStatus.ALLOCATED not in job.task_status_index
                assert len(job.task_status_index[TaskStatus.BINDING]) == 4

    def test_bulk_dispatch_merges_into_existing_binding_bucket(self):
        from kube_batch_tpu.api.job_info import JobInfo

        job = JobInfo(uid="j")
        pre = build_task(namespace="ns", name="pre", req={"cpu": 1.0})
        job.add_task_info(pre)
        job.update_task_status(pre, TaskStatus.BINDING)  # existing bucket
        t = build_task(namespace="ns", name="t0", req={"cpu": 1.0})
        job.add_task_info(t)
        job.update_task_status(t, TaskStatus.ALLOCATED)
        out = lib.bulk_dispatch(
            [job], bytes([1]), TaskStatus.ALLOCATED, TaskStatus.BINDING
        )
        assert [x.name for x in out] == ["t0"]
        binding = job.task_status_index[TaskStatus.BINDING]
        assert set(binding) == {pre.uid, t.uid}  # merged, not replaced
        assert TaskStatus.ALLOCATED not in job.task_status_index

    def test_bulk_res_axpy_mixed_types_raise_premutation(self):
        from kube_batch_tpu.api.resource_info import Resource

        a = Resource(milli_cpu=1000.0, memory=2048.0)
        b = object()  # not a Resource at all
        deltas = np.asarray([[100.0, 10.0], [100.0, 10.0]], np.float64)
        with pytest.raises(TypeError):
            lib.bulk_res_axpy([a, b], deltas, 1)
        assert a.milli_cpu == 1000.0 and a.memory == 2048.0  # untouched

    def test_bulk_res_axpy_applies_dense_dims(self):
        from kube_batch_tpu.api.resource_info import Resource

        rs = [Resource(milli_cpu=1000.0, memory=2048.0) for _ in range(3)]
        deltas = np.asarray(
            [[100.0, 10.0], [200.0, 20.0], [300.0, 30.0]], np.float64
        )
        lib.bulk_res_axpy(rs, deltas, -1)
        assert [r.milli_cpu for r in rs] == [900.0, 800.0, 700.0]
        assert [r.memory for r in rs] == [2038.0, 2028.0, 2018.0]

    def test_finish_columns_matches_python_builds(self):
        tasks = _mk_tasks(5)
        for i, t in enumerate(tasks):
            t.node_name = f"node-{i}"
            t.pod.metadata.creation_timestamp = 100.0 + i
        row_of = {t.uid: r for r, t in enumerate(tasks)}
        task_keys = [f"{t.namespace}/{t.name}" for t in tasks]
        rb, cb, keys, hostnames = lib.finish_columns(
            tasks, row_of, task_keys, TaskStatus.BINDING
        )
        assert np.frombuffer(rb, np.int64).tolist() == list(range(5))
        assert np.frombuffer(cb, np.float64).tolist() == [100.0 + i for i in range(5)]
        assert keys == task_keys
        assert hostnames == [f"node-{i}" for i in range(5)]
        assert all(t.status is TaskStatus.BINDING for t in tasks)

    def test_finish_columns_unencoded_task_keys_lazily(self):
        tasks = _mk_tasks(2)
        for t in tasks:
            t.node_name = "n0"
        row_of = {tasks[0].uid: 0}  # tasks[1] unknown to this encode
        rb, cb, keys, hostnames = lib.finish_columns(
            tasks, row_of, ["ns/p0"], None
        )
        rows = np.frombuffer(rb, np.int64).tolist()
        assert rows == [0, -1]
        assert keys == ["ns/p0", "ns/p1"]
        # None = no flip: status must be UNCHANGED (build_task default)
        assert tasks[0].status is TaskStatus.PENDING
        assert tasks[1].status is TaskStatus.PENDING
