"""Cycle-level tracing, flight recorder, SLO accounting (ISSUE 11).

Covers the tentpole end to end: the span tree of a full cycle and a
streaming micro-cycle, cross-process trace propagation over a live
LoopbackBackend (the federated smoke), the flight-recorder dump landing
during a chaos kill-mid-dispatch drill and staying readable across the
takeover, SLO sliding-window math, Prometheus label escaping against a
golden file, the /debug endpoints, and the zero-cost-off guarantee.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from kube_batch_tpu import faults, metrics, obs
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.cache import StoreBinder
from kube_batch_tpu.cache.store import PODS
from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


@pytest.fixture
def tracing(monkeypatch, tmp_path):
    """Tracing on, flight recorder pointed at tmp, clean slates; the
    switch is armed through the env var because every scheduler cycle
    re-resolves it from conf/env (hot reload)."""
    monkeypatch.setenv(obs.ENV, "1")
    monkeypatch.setenv(obs.RECORDER_ENV, str(tmp_path / "flight"))
    obs.configure()
    obs.recorder.clear()
    obs.recorder._last_dump_mono = 0.0  # undo earlier tests' dump throttle
    obs.slo.reset()
    yield
    obs.configure("off")
    obs.recorder.clear()
    obs.slo.reset()


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


XLA_CONF = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""

STREAM_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: true
"""


def seed_store(store: ClusterStore, gangs: int = 2, members: int = 4,
               nodes: int = 4) -> None:
    store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=32))
        )
    for g in range(gangs):
        store.create_pod_group(build_pod_group(f"g{g}", min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"g{g}-p{m}", group_name=f"g{g}",
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def make_scheduler(store, tmp_path, conf=XLA_CONF, journal=None, binder=None,
                   period=0.05):
    path = tmp_path / "conf.yaml"
    path.write_text(conf)
    cache = SchedulerCache(store, journal=journal, binder=binder)
    return cache, Scheduler(cache, scheduler_conf=str(path), schedule_period=period)


def spans_by_name(spans):
    out: dict[str, list] = {}
    for s in spans:
        out.setdefault(s["name"], []).append(s)
    return out


# -- zero-cost off -----------------------------------------------------------


def test_off_every_entry_point_is_the_noop_singleton():
    assert not obs.enabled()
    assert obs.span("cycle") is obs.NOOP_SPAN
    assert obs.span("cycle", parent=("abc", "def"), attr=1) is obs.NOOP_SPAN
    assert obs.annotate("kbt.solve") is obs.NOOP_SPAN
    assert obs.current() is None
    assert obs.current_headers() == {}
    assert obs.from_headers({obs.HDR_TRACE: "t", obs.HDR_SPAN: "s"}) is None
    obs.event("ignored")  # no current span, no error
    obs.emit("time_to_bind", 0.0, 1.0, queue="q")
    assert obs.recorder.spans() == []


def test_off_cycle_records_nothing(tmp_path):
    assert not obs.enabled()
    store = ClusterStore()
    seed_store(store)
    _, sched = make_scheduler(store, tmp_path)
    sched.run_once()
    assert obs.recorder.spans() == []
    assert all(p.node_name for p in store.list(PODS))


def test_off_overhead_is_one_branch(tmp_path):
    """The hot-path guard: with tracing off, a span open is a module
    bool check returning a singleton. Guard the shape (identity, no
    recorder traffic) and a generous relative timing bound so a future
    allocation on the off path fails loudly."""
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.span("cycle")
    off_cost = (time.perf_counter() - t0) / n
    # microseconds per call, not milliseconds: 50us/call would still
    # pass, an accidental Span() allocation + ring append would not
    assert off_cost < 5e-5


# -- span trees --------------------------------------------------------------


def test_full_cycle_span_tree(tmp_path, tracing):
    journal = WriteIntentJournal(str(tmp_path / "j.wal"))
    store = ClusterStore()
    seed_store(store)
    _, sched = make_scheduler(store, tmp_path, journal=journal)
    sched.run_once()
    wait_until(lambda: all(p.node_name for p in store.list(PODS)),
               what="all pods bound")
    sched.cache.stop()

    spans = obs.recorder.spans()
    assert obs.check_tree(spans) == []
    by = spans_by_name(spans)
    for name in ("cycle", "snapshot", "encode", "solve", "gang.assign",
                 "dispatch", "journal.append", "commit"):
        assert name in by, f"missing {name} span; got {sorted(by)}"
    cycles = [s for s in by["cycle"] if s["attrs"].get("cycle") == 1]
    assert len(cycles) == 1
    root = cycles[0]
    assert root["parent_id"] == ""
    # every span of the scheduling cycle hangs off the one root trace
    cycle_spans = [s for s in spans if s["trace_id"] == root["trace_id"]]
    for name in ("snapshot", "encode", "solve", "dispatch", "journal.append"):
        assert any(s["name"] == name for s in cycle_spans), name
    solve = next(s for s in cycle_spans if s["name"] == "solve")
    assert "tier" in solve["attrs"]
    # the gang.bind spans crossed the kb-write pool but kept the trace
    assert any(s["name"] == "gang.bind" and s["trace_id"] == root["trace_id"]
               for s in spans) or "gang.bind" not in by


def test_journal_records_carry_the_cycle_trace(tmp_path, tracing):
    journal = WriteIntentJournal(str(tmp_path / "j.wal"))
    store = ClusterStore()
    seed_store(store)
    _, sched = make_scheduler(store, tmp_path, journal=journal)
    sched.run_once()
    sched.cache.stop()
    root = next(s for s in obs.recorder.spans() if s["name"] == "cycle")
    with open(journal.path, encoding="utf-8") as fh:
        intents = [json.loads(line) for line in fh
                   if '"rec":"intent"' in line]
    assert intents
    assert all(rec.get("trace") == root["trace_id"] for rec in intents)
    # unknown keys must not break replay
    replay = WriteIntentJournal.replay(journal.path)
    assert replay.corrupt == 0 and len(replay.intents) == len(intents)


def test_micro_cycle_emits_time_to_bind_spans(tmp_path, tracing):
    store = ClusterStore()
    store.create_queue(build_queue("default"))
    for i in range(4):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=32))
        )
    # full-cycle period far longer than the test: every bind after the
    # initial cycle must come from a micro-cycle
    _, sched = make_scheduler(store, tmp_path, conf=STREAM_CONF, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        # arrive AFTER the initial full cycle harvested the resident
        # node table — the gang must bind through a micro-cycle, with
        # its arrival timestamp on record for time_to_bind
        wait_until(lambda: sched._stream_state is not None,
                   what="resident stream state")
        store.create_pod_group(build_pod_group("g0", min_member=3))
        for m in range(3):
            store.create_pod(
                build_pod(
                    name=f"g0-p{m}", group_name="g0",
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )
        wait_until(lambda: all(p.node_name for p in store.list(PODS))
                   and any(s["name"] == "time_to_bind"
                           for s in obs.recorder.spans()),
                   what="binds + time_to_bind spans")
    finally:
        stop.set()
        t.join(timeout=10.0)
    spans = obs.recorder.spans()
    assert obs.check_tree(spans) == []
    ttb = [s for s in spans if s["name"] == "time_to_bind"]
    assert ttb and all(s["attrs"]["queue"] == "default" for s in ttb)
    assert all(s["dur_us"] >= 1 for s in ttb)
    if sched.micro_cycles_run:
        assert any(s["name"] == "micro_cycle" for s in spans)
    # the per-queue SLO window saw the same binds
    snap = obs.slo.snapshot()
    assert snap["time_to_bind"]["default"]["n"] >= len(ttb)


# -- cross-process propagation ----------------------------------------------


def test_header_roundtrip_joins_the_trace(tracing):
    with obs.span("gang.bind") as parent:
        headers = obs.current_headers()
        assert headers[obs.HDR_TRACE] == parent.trace_id
        assert headers[obs.HDR_SPAN] == parent.span_id
    ctx = obs.from_headers(headers)
    child = obs.span("store.bind", parent=ctx)
    with child:
        pass
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.span_id


def test_federated_smoke_joins_conflicted_bind_across_processes(tmp_path):
    """The acceptance drill: a seeded two-shard federated run over live
    LoopbackBackends with a forced stale dispatch — one connected trace
    per conflicted gang bind, Chrome trace exported, tree complete."""
    result = obs.smoke(shards=2, gangs=4, members=3, nodes=6,
                       out_dir=str(tmp_path / "smoke"))
    assert result["ok"], result
    assert result["tree_violations"] == []
    assert result["conflicted_gang_binds"] >= 1
    assert result["remote_spans_joined"] >= 1
    with open(result["chrome_trace"], encoding="utf-8") as fh:
        trace = json.load(fh)
    events = trace["traceEvents"]
    assert any(ev["ph"] == "X" and ev["name"] == "store.bind" for ev in events)
    assert any(ev["ph"] == "s" for ev in events), "missing flow start arrows"
    assert any(ev["ph"] == "f" for ev in events), "missing flow finish arrows"
    with open(result["jsonl"], encoding="utf-8") as fh:
        lines = [json.loads(line) for line in fh]
    assert len(lines) == result["spans"]


# -- flight recorder ---------------------------------------------------------


class _LeaderKilled(BaseException):
    """SIGKILL stand-in (BaseException defeats the retry ladder), same
    contract as the recovery chaos drill."""


class DyingBinder(StoreBinder):
    def __init__(self, store, die_after: int) -> None:
        super().__init__(store)
        self.left = die_after

    def bind(self, pod, hostname: str) -> None:
        if self.left <= 0:
            raise _LeaderKilled()
        self.left -= 1
        super().bind(pod, hostname)


def test_flight_recorder_dump_survives_kill_mid_dispatch(tmp_path, tracing):
    """Chaos: the leader dies mid-dispatch (after journal append, after
    some store writes). The ``bind.slow`` fault firing just before the
    kill snapshots the flight recorder, so the dump on disk holds the
    interrupted cycle's spans — and both the dump and the journal stay
    readable for the standby's takeover."""
    faults.registry.arm("bind.slow", count=1)
    journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    store = ClusterStore()
    seed_store(store, gangs=2, members=6)
    _, sched = make_scheduler(
        store, tmp_path, journal=journal,
        binder=DyingBinder(store, die_after=4),
    )
    with pytest.raises(_LeaderKilled):
        sched.run_once()
    landed = sum(1 for p in store.list(PODS) if p.node_name)
    assert 0 < landed < 12, "kill must land mid-batch"

    dump_dir = obs.recorder.dump_dir()
    dumps = [f for f in os.listdir(dump_dir) if f.endswith(".jsonl")]
    assert dumps, "fault fire must have dumped the ring pre-kill"
    assert any("fault_bind.slow" in f for f in dumps)
    with open(os.path.join(dump_dir, dumps[0]), encoding="utf-8") as fh:
        dumped = [json.loads(line) for line in fh]
    names = {s["name"] for s in dumped}
    # children of the interrupted cycle, finished before the kill
    assert {"snapshot", "encode", "solve", "journal.append"} <= names
    trace_ids = {s["trace_id"] for s in dumped if s["name"] == "solve"}
    assert len(trace_ids) == 1, "one interrupted cycle, one trace"
    # the sibling Chrome trace parses too
    chrome = [f for f in os.listdir(dump_dir) if f.endswith(".trace.json")]
    assert chrome
    with open(os.path.join(dump_dir, chrome[0]), encoding="utf-8") as fh:
        assert json.load(fh)["traceEvents"]

    # standby takeover: journal (with trace links) replays clean
    standby = WriteIntentJournal(str(tmp_path / "leader.wal"))
    report = reconcile_journal(standby, store)
    assert report.redispatched == 12 - landed
    assert all(p.node_name for p in store.list(PODS))


def test_flight_recorder_ring_is_bounded(tracing):
    obs.recorder.resize(4)
    try:
        for i in range(10):
            with obs.span("cycle", i=i):
                pass
        assert obs.recorder.trace_count() == 4
        kept = {s["attrs"]["i"] for s in obs.recorder.spans()}
        assert kept == {6, 7, 8, 9}, "ring must evict oldest traces first"
    finally:
        obs.recorder.resize(256)


def test_dump_throttle_and_disable(tmp_path, tracing, monkeypatch):
    with obs.span("cycle"):
        pass
    assert obs.recorder.dump(reason="first") is not None
    assert obs.recorder.dump(reason="second", min_interval_s=60.0) is None
    monkeypatch.setenv(obs.RECORDER_ENV, "0")
    assert obs.recorder.dump(reason="disabled") is None


# -- SLO accountant ----------------------------------------------------------


def test_slo_window_quantile_math():
    """The sketch-backed window tracks the exact nearest-rank quantiles
    within the sketch's declared relative error (DDSketch alpha = 1%);
    counts stay exact."""
    acc = obs.SLOAccountant(window_s=300.0)
    rel = obs.QuantileSketch.DEFAULT_ALPHA * 1.05
    for v in range(1, 101):
        acc.observe("time_to_bind", "tenant-a", float(v))
    acc.observe("queue_wait", "", 2.5)  # empty queue falls to "default"
    snap = acc.snapshot()
    a = snap["time_to_bind"]["tenant-a"]
    assert a["n"] == 100
    assert a["p50"] == pytest.approx(50.0, rel=rel)
    assert a["p90"] == pytest.approx(90.0, rel=rel)
    assert a["p99"] == pytest.approx(99.0, rel=rel)
    assert snap["queue_wait"]["default"]["n"] == 1
    assert acc.snapshot()["time_to_bind"]["tenant-a"]["window_s"] == 300.0


def test_slo_window_expires_old_observations():
    acc = obs.SLOAccountant(window_s=0.05)
    acc.observe("time_to_bind", "q", 1.0)
    time.sleep(0.08)
    acc.observe("time_to_bind", "q", 9.0)
    snap = acc.snapshot()
    assert snap["time_to_bind"]["q"]["n"] == 1
    assert snap["time_to_bind"]["q"]["p99"] == pytest.approx(
        9.0, rel=obs.QuantileSketch.DEFAULT_ALPHA * 1.05
    )


def test_slo_publish_lands_on_metrics_gauges():
    obs.slo.reset()
    try:
        obs.slo.observe("queue_wait", "gold", 0.25)
        obs.slo.publish()
        got = metrics.slo_queue_wait.value({"queue": "gold", "quantile": "p99"})
        assert got == pytest.approx(0.25, rel=obs.QuantileSketch.DEFAULT_ALPHA * 1.05)
        text = metrics.render_prometheus_text()
        assert 'kube_batch_tpu_slo_queue_wait_seconds{quantile="p50",queue="gold"}' in text
    finally:
        obs.slo.reset()


def test_slo_always_on_even_with_tracing_off():
    assert not obs.enabled()
    obs.slo.reset()
    try:
        obs.slo.observe("time_to_bind", "q", 0.1)
        assert obs.slo.snapshot()["time_to_bind"]["q"]["n"] == 1
    finally:
        obs.slo.reset()


# -- Prometheus text format (satellite: escaping + golden file) ---------------

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "metrics_golden.txt")


def _golden_families():
    h = metrics.Histogram("t_hist_seconds", "a histogram", (0.1, 1.0))
    h.observe(0.05, {"queue": 'say "hi"\nback\\slash'})
    h.observe(5.0, {"queue": 'say "hi"\nback\\slash'})
    h.observe(0.5)
    c = metrics.Counter("t_total", "a counter")
    c.inc({"op": "bind"}, by=3)
    g = metrics.Gauge("t_gauge", "a gauge")
    g.set(1.5, {"queue": "a\\b", "quantile": "p50"})
    return [h, c, g]


def test_metrics_exposition_matches_golden_file():
    """Pin the exact exposition text: label escaping (backslash, quote,
    newline), the +Inf bucket equal to _count, and _sum/_count emitted
    for every label set. Regenerate by running this test with
    KBT_REGEN_GOLDEN=1 after an intentional format change."""
    lines: list[str] = []
    for fam in _golden_families():
        lines.extend(metrics._render_family(fam))
    text = "\n".join(lines) + "\n"
    if os.environ.get("KBT_REGEN_GOLDEN") == "1":  # pragma: no cover
        with open(GOLDEN, "w", encoding="utf-8") as fh:
            fh.write(text)
    with open(GOLDEN, encoding="utf-8") as fh:
        assert text == fh.read()


def test_every_registered_family_exposes_help_and_type():
    """The real exposition (not the synthetic golden families) must
    carry a # HELP and # TYPE pair for every family — including the
    forensics counters — so scrapers never see an undocumented series.
    The KBT-R011 analyzer enforces the declaration side statically;
    this pins the rendered text."""
    metrics.register_unschedulable("ports")
    metrics.register_would_fit_if("ports")
    text = metrics.render_prometheus_text()
    helps = {
        line.split()[2] for line in text.splitlines()
        if line.startswith("# HELP ")
    }
    types = {
        line.split()[2] for line in text.splitlines()
        if line.startswith("# TYPE ")
    }
    assert helps == types and helps
    for name in ("kube_batch_tpu_unschedulable_total",
                 "kube_batch_tpu_would_fit_if_total"):
        assert name in helps, f"{name} missing from exposition"
    # every sample line belongs to a family that announced itself
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        bare = line.split("{")[0].split()[0]
        # histogram samples ride _bucket/_sum/_count suffixes; a family
        # may itself END in one of those (unschedule_task_count), so
        # accept the bare name first and the stripped root second
        candidates = {bare} | {
            bare[: -len(s)]
            for s in ("_bucket", "_sum", "_count")
            if bare.endswith(s)
        }
        assert candidates & helps, f"sample {bare} has no # HELP"


def test_histogram_inf_bucket_equals_count_per_label_set():
    h, _, _ = _golden_families()
    rendered = "\n".join(metrics._render_family(h))
    for labels in ({"queue": 'say "hi"\nback\\slash'}, {}):
        snap = h.snapshot(labels)
        assert snap["count"] == (2 if labels else 1)
    assert rendered.count('le="+Inf"') == 2
    assert rendered.count("t_hist_seconds_sum") == 2
    assert rendered.count("t_hist_seconds_count") == 2
    # escaped, not raw: the newline never appears verbatim in the text
    assert "\nback" not in rendered.replace("\\nback", "")


# -- /debug endpoints + hot reload -------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


def test_debug_endpoints_serve_recorder_and_slo(tmp_path, tracing):
    from kube_batch_tpu.server import SchedulerServer

    server = SchedulerServer(
        scheduler_name="obs-test", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()
    try:
        with obs.span("cycle"):
            pass
        obs.slo.observe("queue_wait", "default", 0.2)
        status, body = _get(server.listen_port, "/debug/trace")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is True
        assert payload["traces"] >= 1
        assert any(s["name"] == "cycle" for s in payload["spans"])
        status, body = _get(server.listen_port, "/debug/slo")
        assert status == 200
        assert json.loads(body)["queue_wait"]["default"]["n"] == 1
        status, body = _get(server.listen_port, "/metrics")
        assert status == 200
        assert "kube_batch_tpu_slo_queue_wait_seconds" in body
    finally:
        server.stop()


def test_conf_trace_key_hot_reloads_the_switch(tmp_path):
    store = ClusterStore()
    seed_store(store, gangs=0)
    conf = tmp_path / "conf.yaml"
    conf.write_text(XLA_CONF + 'trace: "on"\n')
    cache = SchedulerCache(store)
    sched = Scheduler(cache, scheduler_conf=str(conf), schedule_period=0.05)
    try:
        sched._load_conf()
        assert obs.enabled()
        conf.write_text(XLA_CONF + 'trace: "off"\n')
        sched._load_conf()
        assert not obs.enabled()
    finally:
        obs.configure("off")


def test_span_names_registry_matches_reality():
    """Every name the tree checker accepts is declared, and the five
    debug endpoints are exactly the declared surface (the KBT-R analyzer
    enforces the call-site side; this pins the registry's shape)."""
    assert len(obs.SPAN_NAMES) == len(set(obs.SPAN_NAMES))
    assert obs.DEBUG_ENDPOINTS == (
        "/debug/trace", "/debug/slo", "/debug/explain", "/debug/fleet",
        "/debug/admission",
    )
    bad = obs.check_tree([{
        "name": "not-a-span", "trace_id": "t", "span_id": "s",
        "parent_id": "missing",
    }])
    assert len(bad) == 2  # undeclared name + dangling parent
