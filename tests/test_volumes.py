"""Volume lifecycle: assume-at-allocate / bind-at-dispatch over the
in-process store (reference cache.go:165-189 volumebinder wiring,
interface.go:46-56 contract, session.go:241-260 assume and :298-322
bind; PV/PVC/StorageClass informers cache.go:268-297)."""

from __future__ import annotations

import time

import pytest

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import NodeSelectorTerm, VolumePhase
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.cache import StoreVolumeBinder, VolumeBindingError
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_pv,
    build_pvc,
    build_queue,
    build_resource_list,
    build_storage_class,
)
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.apis.types import PodGroupPhase


def inqueue(pg):
    # allocate skips Pending-phase PodGroups (the enqueue action's gate);
    # these tests drive allocate directly
    pg.status.phase = PodGroupPhase.INQUEUE
    return pg

TIERS = parse_scheduler_conf(
    """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""
).tiers


def wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def store():
    s = ClusterStore()
    s.create_queue(build_queue("default"))
    return s


def make_cache(store):
    return SchedulerCache(store)


# -- binder unit behavior ---------------------------------------------------


def test_assume_picks_smallest_fitting_pv(store):
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("n1", build_resource_list(cpu=4)))
    store.create_storage_class(build_storage_class("fast"))
    store.create_persistent_volume(build_pv("big", capacity="100Gi", storage_class="fast"))
    store.create_persistent_volume(build_pv("small", capacity="2Gi", storage_class="fast"))
    store.create_persistent_volume(build_pv("wrong-class", capacity="2Gi", storage_class="slow"))
    store.create_persistent_volume_claim(build_pvc("c1", storage_class="fast", request="1Gi"))

    pod = build_pod(name="p1", req=build_resource_list(cpu=1), volumes=["c1"])
    task = TaskInfo(pod)
    binder.allocate_volumes(task, "n1")
    assert binder._assumed[task.uid] == {"default/c1": "small"}
    assert task.volume_ready is False

    binder.bind_volumes(task)
    assert task.volume_ready is True
    assert store.get("persistentvolumes", "small").claim_ref == "default/c1"
    assert store.get("persistentvolumes", "small").phase == VolumePhase.BOUND
    pvc = store.get("persistentvolumeclaims", "default/c1")
    assert pvc.volume_name == "small" and pvc.phase == VolumePhase.BOUND


def test_assume_respects_pv_topology_and_reservation(store):
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("na", labels={"zone": "a"}))
    store.create_node(build_node("nb", labels={"zone": "b"}))
    store.create_persistent_volume(
        build_pv("pv-a", node_affinity=[NodeSelectorTerm(key="zone", values=["a"])])
    )
    store.create_persistent_volume_claim(build_pvc("c1", request="1Gi"))
    store.create_persistent_volume_claim(build_pvc("c2", request="1Gi"))

    t1 = TaskInfo(build_pod(name="p1", volumes=["c1"]))
    with pytest.raises(VolumeBindingError):
        binder.allocate_volumes(t1, "nb")  # topology mismatch
    binder.allocate_volumes(t1, "na")

    # pv-a is reserved for c1 now; c2 cannot take it
    t2 = TaskInfo(build_pod(name="p2", volumes=["c2"]))
    with pytest.raises(VolumeBindingError):
        binder.allocate_volumes(t2, "na")
    binder.forget(t1.uid)
    binder.allocate_volumes(t2, "na")  # freed by rollback


def test_unknown_claim_fails_assume(store):
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("n1"))
    t = TaskInfo(build_pod(name="p1", volumes=["nope"]))
    with pytest.raises(VolumeBindingError):
        binder.allocate_volumes(t, "n1")


# -- through the live cache + serial action ---------------------------------


def run_allocate(store, action_name="allocate"):
    cache = make_cache(store)
    ssn = open_session(cache, TIERS)
    get_action(action_name).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    cache.stop()
    return state


@pytest.mark.parametrize("action_name", ["allocate", "xla_allocate"])
def test_gang_with_volumes_binds_atomically(store, action_name):
    """A 2-member gang whose pods claim zone-pinned volumes lands each pod
    on the zone its volume lives in, binds atomically, and flips both
    PVCs to Bound (assume at allocate, bind at the gang dispatch)."""
    store.create_node(
        build_node("na", build_resource_list(cpu=4, memory="8Gi", pods=10), labels={"zone": "a"})
    )
    store.create_node(
        build_node("nb", build_resource_list(cpu=4, memory="8Gi", pods=10), labels={"zone": "b"})
    )
    store.create_persistent_volume(
        build_pv("pv-a", node_affinity=[NodeSelectorTerm(key="zone", values=["a"])])
    )
    store.create_persistent_volume(
        build_pv("pv-b", node_affinity=[NodeSelectorTerm(key="zone", values=["b"])])
    )
    store.create_persistent_volume_claim(build_pvc("ca", request="1Gi"))
    store.create_persistent_volume_claim(build_pvc("cb", request="1Gi"))
    store.create_pod_group(inqueue(build_pod_group("pg", min_member=2)))
    store.create_pod(
        build_pod(name="pa", group_name="pg", req=build_resource_list(cpu=1, memory="1Gi"),
                  node_selector={"zone": "a"}, volumes=["ca"])
    )
    store.create_pod(
        build_pod(name="pb", group_name="pg", req=build_resource_list(cpu=1, memory="1Gi"),
                  node_selector={"zone": "b"}, volumes=["cb"])
    )

    state = run_allocate(store, action_name)
    # The session sees BINDING; the Bound flip arrives in the *cache* via
    # the store's watch echo (bind round-trip), so assert the durable
    # store state for the rest.
    assert state["default-pa"][0] in (TaskStatus.BINDING, TaskStatus.BOUND)
    assert state["default-pa"][1] == "na"
    assert state["default-pb"][1] == "nb"
    assert store.get("persistentvolumeclaims", "default/ca").volume_name == "pv-a"
    assert store.get("persistentvolumeclaims", "default/cb").volume_name == "pv-b"
    assert store.get("persistentvolumes", "pv-a").phase == VolumePhase.BOUND
    assert store.get_pod("default", "pa").node_name == "na"


@pytest.mark.parametrize("action_name", ["allocate", "xla_allocate"])
def test_unsatisfiable_claim_leaves_task_pending(store, action_name):
    """WaitForFirstConsumer with no pre-provisioned PV: the assume fails,
    the task stays Pending, and the cycle (and the other job) survives."""
    store.create_storage_class(build_storage_class("wffc", mode="WaitForFirstConsumer"))
    store.create_node(build_node("n1", build_resource_list(cpu=4, memory="8Gi", pods=10)))
    store.create_persistent_volume_claim(build_pvc("c1", storage_class="wffc"))
    store.create_pod_group(inqueue(build_pod_group("pg-vol", min_member=1)))
    store.create_pod(
        build_pod(name="vol-pod", group_name="pg-vol",
                  req=build_resource_list(cpu=1, memory="1Gi"), volumes=["c1"])
    )
    store.create_pod_group(inqueue(build_pod_group("pg-plain", min_member=1)))
    store.create_pod(
        build_pod(name="plain-pod", group_name="pg-plain",
                  req=build_resource_list(cpu=1, memory="1Gi"))
    )

    state = run_allocate(store, action_name)
    assert state["default-vol-pod"] == (TaskStatus.PENDING, "")
    assert state["default-plain-pod"][0] in (TaskStatus.BINDING, TaskStatus.BOUND)
    assert state["default-plain-pod"][1] == "n1"
    assert store.get_pod("default", "vol-pod").node_name == ""
    assert store.get_pod("default", "plain-pod").node_name == "n1"


def test_failed_volume_bind_resyncs_task(store):
    """An assumed PV that vanishes before dispatch: bind_volumes raises,
    the task routes through errTasks, and the resync returns it to
    Pending (reference cache.go:512-534 self-heal)."""
    cache = make_cache(store)
    cache.run()
    try:
        store.create_node(build_node("n1", build_resource_list(cpu=4, memory="8Gi", pods=10)))
        store.create_persistent_volume(build_pv("pv1"))
        store.create_persistent_volume_claim(build_pvc("c1", request="1Gi"))
        store.create_pod_group(inqueue(build_pod_group("pg", min_member=2)))
        store.create_pod(
            build_pod(name="p1", group_name="pg",
                      req=build_resource_list(cpu=1, memory="1Gi"), volumes=["c1"])
        )
        store.create_pod(
            build_pod(name="p2", group_name="pg", req=build_resource_list(cpu=1, memory="1Gi"))
        )

        ssn = open_session(cache, TIERS)
        job = next(iter(ssn.jobs.values()))
        t1 = next(t for t in job.tasks.values() if t.name == "p1")
        t2 = next(t for t in job.tasks.values() if t.name == "p2")
        ssn.allocate(t1, "n1")  # assumes pv1
        store.delete_persistent_volume("pv1")  # yanked before dispatch
        with pytest.raises(VolumeBindingError):
            ssn.allocate(t2, "n1")  # gang ready -> dispatch -> bind fails
        close_session(ssn)

        wait_until(
            lambda: next(
                t.status
                for j in cache.jobs.values()
                for t in j.tasks.values()
                if t.name == "p1"
            )
            == TaskStatus.PENDING,
            what="errTasks resync back to Pending",
        )
    finally:
        cache.stop()


def test_two_claims_one_pod_distinct_pvs(store):
    """Sibling claims of one pod must land on distinct PVs even when the
    smallest PV matches both (round-4 review finding)."""
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("n1"))
    store.create_persistent_volume(build_pv("small", capacity="2Gi"))
    store.create_persistent_volume(build_pv("big", capacity="20Gi"))
    store.create_persistent_volume_claim(build_pvc("c1", request="1Gi"))
    store.create_persistent_volume_claim(build_pvc("c2", request="1Gi"))
    t = TaskInfo(build_pod(name="p1", volumes=["c1", "c2"]))
    binder.allocate_volumes(t, "n1")
    assert sorted(binder._assumed[t.uid].values()) == ["big", "small"]
    binder.bind_volumes(t)
    assert store.get("persistentvolumeclaims", "default/c1").volume_name == "small"
    assert store.get("persistentvolumeclaims", "default/c2").volume_name == "big"


def test_failed_bind_keeps_assumptions_for_retry(store):
    """A failed bind must not destroy the assumption record: the retry
    re-attempts the real writes instead of vacuously succeeding."""
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("n1"))
    store.create_persistent_volume(build_pv("pv1"))
    store.create_persistent_volume_claim(build_pvc("c1", request="1Gi"))
    t = TaskInfo(build_pod(name="p1", volumes=["c1"]))
    binder.allocate_volumes(t, "n1")
    store.delete_persistent_volume("pv1")
    with pytest.raises(VolumeBindingError):
        binder.bind_volumes(t)
    assert binder._assumed[t.uid] == {"default/c1": "pv1"}  # record survives
    with pytest.raises(VolumeBindingError):
        binder.bind_volumes(t)  # still fails, does NOT bind pod sans volume
    # PV restored (e.g. re-created by an operator): retry succeeds
    store.create_persistent_volume(build_pv("pv1"))
    binder.bind_volumes(t)
    assert store.get("persistentvolumeclaims", "default/c1").volume_name == "pv1"


def test_bound_claim_pins_pod_to_volume_topology(store):
    """A claim already Bound (mirrored from an existing cluster) pins its
    pod to nodes the PV tolerates — the assume's bound-claim branch."""
    import dataclasses as dc

    from kube_batch_tpu.apis.types import VolumePhase

    binder = StoreVolumeBinder(store)
    store.create_node(build_node("na", labels={"zone": "a"}))
    store.create_node(build_node("nb", labels={"zone": "b"}))
    pv = build_pv("pv-a", node_affinity=[NodeSelectorTerm(key="zone", values=["a"])])
    store.create_persistent_volume(dc.replace(pv, claim_ref="default/c1", phase=VolumePhase.BOUND))
    pvc = build_pvc("c1", request="1Gi")
    pvc.volume_name = "pv-a"
    pvc.phase = VolumePhase.BOUND
    store.create_persistent_volume_claim(pvc)
    t = TaskInfo(build_pod(name="p1", volumes=["c1"]))
    with pytest.raises(VolumeBindingError):
        binder.allocate_volumes(t, "nb")
    binder.allocate_volumes(t, "na")
    assert t.volume_ready is True  # nothing left to bind


def test_unknown_storage_class_fails_assume(store):
    binder = StoreVolumeBinder(store)
    store.create_node(build_node("n1"))
    store.create_persistent_volume_claim(build_pvc("c1", storage_class="no-such-class"))
    t = TaskInfo(build_pod(name="p1", volumes=["c1"]))
    with pytest.raises(VolumeBindingError):
        binder.allocate_volumes(t, "n1")
