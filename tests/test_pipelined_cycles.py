"""Pipelined scheduling cycles (ISSUE 13, ``KBT_PIPELINE``).

The overlapped encode/solve/dispatch path must be invisible except in
wall-clock: every test here states an equality against the synchronous
path the feature is allowed to overlap but never allowed to change.

- **fence semantics**: arm/wait rendezvous, overlap accounting, sticky
  loud degradation on timeout / fault / dispatch exception, re-join of
  a wedged future, reset hygiene;
- **chaos**: the ``pipeline.fence`` fault point mid-overlap degrades
  the process to synchronous cycles with zero lost and zero duplicate
  binds (detector armed suite-wide by conftest);
- **parity**: KBT_PIPELINE x streaming micro-cycles place bind-for-bind
  identically to the plain periodic synchronous loop over the same
  arrivals;
- **crash consistency**: a leader killed inside the deferred dispatch
  leaves the PR-3 write-intent journal holding the in-flight suffix,
  and a standby's reconciliation + one full cycle converge to the
  uninterrupted twin with zero lost and zero duplicate binds;
- **arena**: the double-buffered ``TensorArena`` ping-pongs banks per
  cycle and stays byte-identical to the host arrays it mirrors.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np
import pytest

from kube_batch_tpu import faults, metrics, pipeline
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.cache import StoreBinder
from kube_batch_tpu.cache.store import PODS, EventHandler
from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

_ENV_KEYS = (pipeline.ENV, pipeline.FENCE_TIMEOUT_ENV, "KBT_EXCHANGE_BATCH")


@pytest.fixture(autouse=True)
def _clean_world():
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    faults.registry.reset()
    faults.solver_ladder.reset()
    pipeline.reset()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    pipeline.reset()
    faults.registry.reset()
    faults.solver_ladder.reset()


def wait_until(pred, timeout=20.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# The conf every e2e below schedules with: allocation routed through
# xla_allocate (the only action with a deferrable post-solve phase),
# min_device_pairs 0 so the tiny model clusters stay on the device path
# (the same pin the sharded parity suites use), and no drf/proportion
# so streaming micro-tiers and full cycles state exact parity.
PIPE_CONF = """
actions: "enqueue, xla_allocate, backfill"
actionArguments:
  xla_allocate:
    min_device_pairs: "0"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: {streaming}
"""


def seed_cluster(store: ClusterStore, nodes: int = 4) -> None:
    store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=64))
        )


def arrive_gang(store: ClusterStore, name: str, members: int) -> None:
    store.create_pod_group(build_pod_group(name, min_member=members))
    for m in range(members):
        store.create_pod(
            build_pod(
                name=f"{name}-p{m}", group_name=name,
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
        )


def make_scheduler(store, tmp_path, streaming=False, period=5.0,
                   journal=None, binder=None):
    conf = tmp_path / f"conf-{streaming}.yaml"
    conf.write_text(PIPE_CONF.format(streaming=str(streaming).lower()))
    cache = SchedulerCache(store, journal=journal, binder=binder)
    return cache, Scheduler(cache, scheduler_conf=str(conf), schedule_period=period)


def placements(store) -> dict:
    return {f"{p.namespace}/{p.name}": p.node_name for p in store.list(PODS)}


def all_bound(store) -> bool:
    pods = store.list(PODS)
    return bool(pods) and all(p.node_name for p in pods)


def count_bind_events(store) -> dict:
    counts: dict[str, int] = {}

    def on_update(old, new):
        if not old.node_name and new.node_name:
            key = f"{new.namespace}/{new.name}"
            counts[key] = counts.get(key, 0) + 1

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    return counts


# -- fence units --------------------------------------------------------------


def test_fence_clean_wait_clears_and_records_overlap():
    os.environ[pipeline.ENV] = "1"
    assert pipeline.enabled()
    assert pipeline.fence.wait(), "nothing armed must be a clean wait"

    fut: Future = Future()
    fut.set_result(None)
    pipeline.fence.arm(fut)
    pipeline.fence.record_dispatch_seconds(0.25)
    assert not pipeline.fence.pending()  # armed but already landed
    assert pipeline.fence.wait()
    assert pipeline.fence.degraded_reason is None
    # dispatch landed before the wait started: full overlap
    assert metrics.pipeline_overlap_fraction.value() == pytest.approx(1.0, abs=0.05)
    # a clean wait disarms: the next wait has nothing to join
    assert pipeline.fence.wait()


def test_fence_timeout_is_sticky_and_keeps_the_future_armed():
    os.environ[pipeline.ENV] = "1"
    pool = ThreadPoolExecutor(max_workers=1)
    release = threading.Event()
    try:
        fut = pool.submit(release.wait, 10.0)
        pipeline.fence.arm(fut)
        assert pipeline.enabled()
        assert not pipeline.fence.wait(timeout=0.05)
        assert "timeout" in pipeline.fence.degraded_reason
        assert not pipeline.enabled(), "degradation must be sticky"
        assert pipeline.fence.pending(), "wedged future must stay armed"
        # the dispatch eventually lands; the next (synchronous) cycle
        # re-joins it cleanly -- but the process stays degraded
        release.set()
        fut.result(timeout=5.0)
        assert pipeline.fence.wait()
        assert not pipeline.enabled()
        pipeline.reset()
        assert pipeline.enabled(), "reset is the only way back"
    finally:
        release.set()
        pool.shutdown(wait=True)


def test_fence_fault_point_degrades():
    os.environ[pipeline.ENV] = "1"
    fut: Future = Future()
    fut.set_result(None)
    pipeline.fence.arm(fut)
    faults.registry.arm("pipeline.fence", count=1)
    assert not pipeline.fence.wait()
    assert "pipeline.fence" in pipeline.fence.degraded_reason
    assert not pipeline.enabled()


def test_fence_dispatch_exception_degrades_and_disarms():
    os.environ[pipeline.ENV] = "1"
    fut: Future = Future()
    fut.set_exception(RuntimeError("replay exploded"))
    pipeline.fence.arm(fut)
    assert not pipeline.fence.wait()
    assert "RuntimeError" in pipeline.fence.degraded_reason
    assert not pipeline.fence.pending(), "a raised dispatch is finished"


def test_submit_uses_cache_pool_else_module_fallback():
    ran = []
    # SchedulerCache without run(): submit_dispatch executes inline and
    # hands back an already-done future (synchronous degenerate case)
    cache = SchedulerCache(ClusterStore())
    fut = pipeline.submit(cache, lambda: ran.append("inline"))
    assert fut.done() and ran == ["inline"]

    # an inline dispatch that dies carries the exception in the future
    # instead of raising at submission (the fence join re-raises it)
    def boom():
        raise ValueError("carried")

    assert isinstance(pipeline.submit(cache, boom).exception(), ValueError)

    # objects with no submit_dispatch ride the module fallback thread
    class PoolLess:
        pass

    fut2 = pipeline.submit(PoolLess(), lambda: ran.append("fallback"))
    fut2.result(timeout=5.0)
    assert ran[-1] == "fallback"


def test_join_session_reraises_and_pops():
    class S:
        pass

    ssn = S()
    fut: Future = Future()
    fut.set_exception(ValueError("deferred death"))
    ssn.deferred_dispatch = fut
    with pytest.raises(ValueError):
        pipeline.join_session(ssn)
    assert ssn.deferred_dispatch is None
    pipeline.join_session(S())  # no deferred work: no-op


# -- arena double-buffering ---------------------------------------------------


def test_arena_bank_pingpong_matches_hosts():
    from kube_batch_tpu.ops.encode_cache import TensorArena

    base = np.arange(32, dtype=np.float32).reshape(8, 4)

    # synchronous mode: the bank is pinned at 0
    arena = TensorArena()
    arena.device_view({"node_idle": base})
    arena.device_view({"node_idle": base})
    assert arena.bank == 0
    assert arena.reuses >= 1  # same object: outright reuse

    os.environ[pipeline.ENV] = "1"
    arena = TensorArena()
    a1 = {"node_idle": base}
    v1 = arena.device_view(a1)
    b1 = arena.bank
    a2 = {"node_idle": base.copy()}
    a2["node_idle"][3] += 1.0
    v2 = arena.device_view(a2)
    assert arena.bank != b1, "pipelined uploads must ping-pong banks"
    # cycle N+1's upload never touched the bank cycle N still reads
    np.testing.assert_array_equal(np.asarray(v1["node_idle"]), a1["node_idle"])
    np.testing.assert_array_equal(np.asarray(v2["node_idle"]), a2["node_idle"])
    # third cycle returns to the first bank: the row delta runs against
    # that bank's own (two-cycles-old) memo and stays byte-identical
    a3 = {"node_idle": a2["node_idle"].copy()}
    a3["node_idle"][5] -= 2.0
    v3 = arena.device_view(a3)
    assert arena.bank == b1
    np.testing.assert_array_equal(np.asarray(v3["node_idle"]), a3["node_idle"])
    assert arena.full_uploads == 2, "one cold upload per bank"
    assert arena.row_updates == 1, "the re-visit scatters rows in place"
    assert arena.rows_uploaded == 2  # rows 3 and 5 vs the bank's memo


# -- chaos: fault mid-overlap degrades to synchronous -------------------------


def test_chaos_fence_fault_mid_overlap_degrades_cleanly(tmp_path):
    """Cycle N defers its dispatch; the ``pipeline.fence`` fault ambushes
    cycle N+1's fence wait. The cycle is skipped, the pipeline degrades
    (sticky, loud), and the following synchronous cycles keep binding:
    zero lost, zero duplicate binds."""
    os.environ[pipeline.ENV] = "1"
    store = ClusterStore()
    seed_cluster(store)
    bind_counts = count_bind_events(store)
    _, sched = make_scheduler(store, tmp_path)

    arrive_gang(store, "g0", members=3)
    sched.run_once()
    assert pipeline.fence._dispatch_s > 0.0, (
        "the first cycle never deferred its dispatch -- the pipelined "
        "path did not engage and this test would check nothing"
    )
    assert all_bound(store)

    faults.registry.arm("pipeline.fence", count=1)
    arrive_gang(store, "g1", members=3)
    sched.run_once()  # fence wait fires the fault: cycle skipped
    assert "pipeline.fence" in pipeline.fence.degraded_reason
    assert not pipeline.enabled()
    _, _, fired = faults.registry.active()["pipeline.fence"]
    assert fired == 1

    sched.run_once()  # synchronous backstop serves the skipped arrivals
    arrive_gang(store, "g2", members=3)
    sched.run_once()
    assert all_bound(store)
    assert len(bind_counts) == 9
    assert all(n == 1 for n in bind_counts.values()), f"duplicate binds: {bind_counts}"


# -- parity: pipelined x streaming vs the periodic synchronous loop -----------


def test_pipelined_streaming_parity_vs_periodic_loop(tmp_path):
    """The same gang arrivals through (a) KBT_PIPELINE + streaming
    micro-cycles, (b) KBT_PIPELINE periodic full cycles, and (c) the
    plain synchronous periodic loop must place bind-for-bind
    identically -- overlap buys wall-clock, never different binds."""
    gangs = [(f"g{i}", 2 + (i % 3)) for i in range(5)]

    def run(pipelined: bool, streaming: bool) -> tuple[dict, Scheduler]:
        pipeline.reset()
        if pipelined:
            os.environ[pipeline.ENV] = "1"
        else:
            os.environ.pop(pipeline.ENV, None)
        store = ClusterStore()
        seed_cluster(store, nodes=6)
        _, sched = make_scheduler(
            store, tmp_path, streaming=streaming,
            period=0.25 if streaming else 0.02,
        )
        stop = threading.Event()
        t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
        t.start()
        try:
            for name, members in gangs:
                arrive_gang(store, name, members)
                time.sleep(0.002)
            wait_until(lambda: all_bound(store), what="all gangs bound")
        finally:
            stop.set()
            t.join(timeout=10.0)
        assert pipeline.fence.degraded_reason is None
        return placements(store), sched

    pipe_stream, stream_sched = run(pipelined=True, streaming=True)
    assert stream_sched.micro_cycles_run > 0, "streaming run never took the micro path"
    pipe_full, _ = run(pipelined=True, streaming=False)
    assert pipeline.fence._dispatch_s > 0.0, (
        "the pipelined periodic run never deferred a dispatch"
    )
    sync_full, _ = run(pipelined=False, streaming=False)
    assert pipe_full == sync_full, "pipelined cycles changed placements"
    assert pipe_stream == sync_full, "pipelined streaming changed placements"


# -- crash consistency: killed inside the deferred dispatch -------------------


class _LeaderKilled(BaseException):
    """SIGKILL stand-in: BaseException so no retry/resync ladder can
    'survive' it -- the dispatch dies exactly where a killed process
    would (same device as the streaming crash e2e)."""


class DyingBinder(StoreBinder):
    def __init__(self, store, die_after: int) -> None:
        super().__init__(store)
        self.left = die_after

    def bind(self, pod, hostname: str) -> None:
        if self.left <= 0:
            raise _LeaderKilled()
        self.left -= 1
        super().bind(pod, hostname)


def test_chaos_leader_killed_mid_deferred_dispatch_journal_reconciles(tmp_path):
    """The leader dies inside cycle N's deferred replay/dispatch (after
    journal appends, after some store writes landed). The PR-3 journal
    holds the in-flight suffix; a standby's reconciliation plus one
    ordinary synchronous cycle converge to the uninterrupted twin's
    placements: zero lost, zero duplicate."""
    total = 12  # 2 gangs x 6

    # uninterrupted twin: plain synchronous cycle over the full arrival set
    twin = ClusterStore()
    seed_cluster(twin)
    for g in range(2):
        arrive_gang(twin, f"g{g}", members=6)
    _, sched_t = make_scheduler(twin, tmp_path)
    sched_t.run_once()
    expected = placements(twin)
    assert all(expected.values()) and len(expected) == total

    # the real run: pipelined, the binder dies after 4 binds. The cache
    # has no writer pool, so the deferred closure runs at submission and
    # carries the death in its future -- close_session's join re-raises
    # it on the scheduler thread, exactly where a fence join would.
    os.environ[pipeline.ENV] = "1"
    pipeline.reset()
    store = ClusterStore()
    seed_cluster(store)
    bind_counts = count_bind_events(store)
    journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    _, sched = make_scheduler(
        store, tmp_path,
        journal=journal, binder=DyingBinder(store, die_after=4),
    )
    for g in range(2):
        arrive_gang(store, f"g{g}", members=6)
    with pytest.raises(_LeaderKilled):
        sched.run_once()
    landed = {k: v for k, v in placements(store).items() if v}
    assert 0 < len(landed) < total, "kill must land mid-dispatch"
    orphans = WriteIntentJournal.replay(journal.path).orphans
    assert orphans, "journal must hold the in-flight suffix"

    # standby: reconcile the journal, then one synchronous full cycle
    pipeline.reset()
    os.environ.pop(pipeline.ENV, None)
    standby_journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    report = reconcile_journal(standby_journal, store)
    assert report.redispatched == len(orphans)
    assert report.rolled_back == 0
    _, sched_b = make_scheduler(store, tmp_path)
    sched_b.run_once()

    assert placements(store) == expected, "standby must converge to the twin"
    assert all(n == 1 for n in bind_counts.values()), f"duplicate binds: {bind_counts}"
    assert set(bind_counts) == set(expected), "lost binds"
    standby_journal.close()
