"""Pipeline-level property test (VERDICT r3 item 5): the full TPU conf
(examples/scheduler-conf-tpu.yaml — xla actions + tensorscore) must
produce the identical session outcome to the serial reference pipeline
(enqueue, reclaim, allocate, backfill, preempt + nodeorder) on random
snapshots — the whole cycle, not one action in isolation."""

from __future__ import annotations

import os

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.conf import parse_scheduler_conf, read_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import FakeCache

from test_xla_preempt import gen_contended_cluster
from test_xla_reclaim import gen_contended_reclaim_cluster

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")

SERIAL_CONF = """
actions: "enqueue, reclaim, allocate, backfill, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_pipeline(conf, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, conf.tiers)
    for name in (a.strip() for a in conf.actions.split(",") if a.strip()):
        get_action(name).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return state, dict(cache.binder.binds), list(cache.evictor.evicts)


def test_tpu_conf_full_pipeline_parity():
    tpu_conf = parse_scheduler_conf(
        read_scheduler_conf(os.path.join(EXAMPLES, "scheduler-conf-tpu.yaml"))
    )
    serial_conf = parse_scheduler_conf(SERIAL_CONF)
    assert tpu_conf.actions.replace("xla_", "") == serial_conf.actions

    total_binds = total_evicts = 0
    for seed in range(12):
        for gen in (gen_contended_cluster, gen_contended_reclaim_cluster):
            serial = run_pipeline(serial_conf, gen(seed))
            tpu = run_pipeline(tpu_conf, gen(seed))
            assert tpu == serial, f"{gen.__name__} seed {seed} diverged"
            total_binds += len(serial[1])
            total_evicts += len(serial[2])
    assert total_binds > 10 and total_evicts > 10, (
        f"sweep too tame ({total_binds} binds, {total_evicts} evicts)"
    )
