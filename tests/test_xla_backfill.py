"""xla_backfill ≡ backfill: the vectorized BestEffort scan's oracle.

The serial backfill action is the reference implementation
(backfill.go:41-76 semantics); these tests assert the group-dedup'd
scan (actions/xla_backfill.py) places the same tasks on the same nodes
in the same order across the predicate edges the scan models — node
selectors, taints/tolerations, cordon, max-task pressure, host ports —
and that pod-affinity tasks and out-of-envelope confs route through the
serial chain."""

import random

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.apis.types import (
    Affinity,
    PodAffinityTerm,
    PodPhase,
    Taint,
    Toleration,
)
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

TIERS = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_and_capture(action_name, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(TIERS).tiers)
    get_action(action_name).execute(ssn)
    state = {}
    for job in ssn.jobs.values():
        for tasks in job.task_status_index.values():
            for t in tasks.values():
                state[t.uid] = (t.status, t.node_name)
    node_tasks = {
        name: sorted(n.tasks) for name, n in ssn.nodes.items()
    }
    close_session(ssn)
    return state, node_tasks, dict(cache.binder.binds)


def assert_equivalent(make_cluster):
    s = run_and_capture("backfill", make_cluster())
    x = run_and_capture("xla_backfill", make_cluster())
    assert x == s
    return s


def _be_pod(name, **kw):
    """BestEffort pod: zero requests (backfill's only clientele)."""
    return build_pod(name=name, req=None, **kw)


def test_places_best_effort_on_first_node():
    pods = [_be_pod(f"be{i}", group_name="g") for i in range(4)]
    nodes = [build_node(f"n{i}", alloc={"cpu": 1.0, "pods": 110}) for i in range(3)]
    s = assert_equivalent(
        lambda: build_cluster(
            pods, nodes, [build_pod_group("g", min_member=1)], [build_queue("default")]
        )
    )
    # every task landed, first-node-in-name-order semantics
    state, node_tasks, _ = s
    assert all(host == "n0" for _, host in state.values())


def test_selector_and_taint_edges():
    def mk():
        pods = []
        for i in range(6):
            p = _be_pod(f"sel{i}", group_name="g", node_selector={"zone": "a"})
            pods.append(p)
        for i in range(6):
            p = _be_pod(f"tol{i}", group_name="g")
            p.tolerations.append(Toleration(key="dedicated", operator="Exists"))
            pods.append(p)
        plain = [_be_pod(f"plain{i}", group_name="g") for i in range(6)]
        pods.extend(plain)
        nodes = [
            build_node("a0", alloc={"cpu": 1.0, "pods": 110}, labels={"zone": "a"}),
            build_node("b0", alloc={"cpu": 1.0, "pods": 110}, labels={"zone": "b"}),
            build_node("t0", alloc={"cpu": 1.0, "pods": 110}),
        ]
        nodes[2].taints.append(Taint(key="dedicated", effect="NoSchedule"))
        cordoned = build_node("c0", alloc={"cpu": 1.0, "pods": 110})
        cordoned.unschedulable = True
        nodes.append(cordoned)
        return build_cluster(
            pods, nodes, [build_pod_group("g", min_member=1)], [build_queue("default")]
        )

    state, node_tasks, _ = assert_equivalent(mk)
    by_host = {}
    for _, host in state.values():
        by_host[host] = by_host.get(host, 0) + 1
    assert by_host.get("c0", 0) == 0  # cordoned node untouched
    # selector pods can only sit on a0; tolerating pods may use t0
    assert all(host == "a0" for uid, (st, host) in state.items() if "sel" in uid)


def test_max_task_pressure_spills_to_next_node():
    def mk():
        pods = [_be_pod(f"be{i}", group_name="g") for i in range(8)]
        nodes = [
            build_node("n0", alloc={"cpu": 1.0, "pods": 3}),
            build_node("n1", alloc={"cpu": 1.0, "pods": 10}),
        ]
        return build_cluster(
            pods, nodes, [build_pod_group("g", min_member=1)], [build_queue("default")]
        )

    state, node_tasks, _ = assert_equivalent(mk)
    assert len(node_tasks["n0"]) == 3 and len(node_tasks["n1"]) == 5


def test_host_port_conflicts_spread():
    def mk():
        pods = []
        for i in range(3):
            p = _be_pod(f"port{i}", group_name="g")
            p.containers[0].ports = [8080]
            pods.append(p)
        nodes = [build_node(f"n{i}", alloc={"cpu": 1.0, "pods": 110}) for i in range(4)]
        return build_cluster(
            pods, nodes, [build_pod_group("g", min_member=1)], [build_queue("default")]
        )

    state, node_tasks, _ = assert_equivalent(mk)
    hosts = [host for _, host in state.values()]
    assert len(set(hosts)) == 3  # one port-8080 pod per node


def test_resident_port_blocks_node():
    def mk():
        running = _be_pod("res", group_name="gr", node_name="n0", phase=PodPhase.RUNNING)
        running.containers[0].ports = [9090]
        newp = _be_pod("new", group_name="g")
        newp.containers[0].ports = [9090]
        nodes = [build_node("n0", alloc={"cpu": 1.0, "pods": 110}), build_node("n1", alloc={"cpu": 1.0, "pods": 110})]
        return build_cluster(
            [running, newp],
            nodes,
            [build_pod_group("g", min_member=1), build_pod_group("gr", min_member=1)],
            [build_queue("default")],
        )

    state, node_tasks, _ = assert_equivalent(mk)
    assert state["default-new"][1] == "n1"


def test_pod_affinity_tasks_step_serially():
    def mk():
        anchor = _be_pod(
            "anchor", group_name="ga", node_name="n1", phase=PodPhase.RUNNING,
            labels={"app": "db"},
        )
        follower = _be_pod("follower", group_name="g")
        follower.affinity = Affinity(
            pod_affinity_required=[
                PodAffinityTerm(
                    label_selector={"app": "db"},
                    topology_key="kubernetes.io/hostname",
                )
            ]
        )
        nodes = [
            build_node("n0", alloc={"cpu": 1.0, "pods": 110}),
            build_node("n1", alloc={"cpu": 1.0, "pods": 110}),
        ]
        return build_cluster(
            [anchor, follower],
            nodes,
            [build_pod_group("g", min_member=1), build_pod_group("ga", min_member=1)],
            [build_queue("default")],
        )

    state, node_tasks, _ = assert_equivalent(mk)
    assert state["default-follower"][1] == "n1"  # required affinity honored


def test_skips_non_best_effort_and_pending_groups():
    def mk():
        pods = [
            build_pod(name="heavy", req={"cpu": 1.0}, group_name="g"),
            _be_pod("light", group_name="g"),
            _be_pod("gated", group_name="pending-g"),
        ]
        nodes = [build_node("n0", alloc={"cpu": 4.0, "pods": 110})]
        cluster = build_cluster(
            pods,
            nodes,
            [build_pod_group("g", min_member=1), build_pod_group("pending-g", min_member=1)],
            [build_queue("default")],
        )
        # keep pending-g in Pending phase (build_cluster promotes to Inqueue)
        from kube_batch_tpu.apis.types import PodGroupPhase

        cluster.jobs["default/pending-g"].pod_group.status.phase = PodGroupPhase.PENDING
        return cluster

    state, node_tasks, _ = assert_equivalent(mk)
    assert state["default-light"][1] == "n0"
    assert state["default-heavy"][1] == ""  # not backfill's business
    assert state["default-gated"][1] == ""  # gated behind enqueue


def test_randomized_parity_sweep():
    zones = ["a", "b", "c"]

    def mk(seed):
        rng = random.Random(seed)
        pods = []
        for i in range(rng.randint(10, 60)):
            kind = rng.random()
            p = _be_pod(f"be{i}", group_name=f"g{i % 5}")
            if kind < 0.25:
                p.node_selector.update({"zone": rng.choice(zones)})
            elif kind < 0.4:
                p.tolerations.append(Toleration(key="dedicated", operator="Exists"))
            elif kind < 0.5:
                p.containers[0].ports = [rng.choice([80, 443, 8080])]
            pods.append(p)
        nodes = []
        for i in range(rng.randint(3, 12)):
            node = build_node(
                f"n{i:02d}",
                alloc={"cpu": 1.0, "pods": rng.choice([2, 4, 110])},
                labels={"zone": rng.choice(zones)},
            )
            if rng.random() < 0.2:
                node.taints.append(Taint(key="dedicated", effect="NoSchedule"))
            if rng.random() < 0.1:
                node.unschedulable = True
            nodes.append(node)
        return build_cluster(
            pods,
            nodes,
            [build_pod_group(f"g{i}", min_member=1) for i in range(5)],
            [build_queue("default")],
        )

    for seed in range(24):
        assert_equivalent(lambda: mk(seed))


def test_out_of_envelope_conf_falls_back_serial():
    no_predicates = """
tiers:
- plugins:
  - name: priority
  - name: gang
"""
    tiers = parse_scheduler_conf(no_predicates).tiers

    def run(action_name):
        pods = [_be_pod(f"be{i}", group_name="g") for i in range(5)]
        nodes = [build_node(f"n{i}", alloc={"cpu": 1.0, "pods": 110}) for i in range(2)]
        cluster = build_cluster(
            pods, nodes, [build_pod_group("g", min_member=1)], [build_queue("default")]
        )
        cache = FakeCache(cluster)
        ssn = open_session(cache, tiers)
        get_action(action_name).execute(ssn)
        state = {
            t.uid: (t.status, t.node_name)
            for job in ssn.jobs.values()
            for tasks in job.task_status_index.values()
            for t in tasks.values()
        }
        close_session(ssn)
        return state

    assert run("xla_backfill") == run("backfill")
