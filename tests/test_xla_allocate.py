"""xla_allocate ≡ allocate: the XLA path's correctness oracle.

The serial allocate action is the reference implementation (itself pinned
against actions/allocate/allocate_test.go in test_actions.py); these
tests assert the jitted solve produces the *same assignments in the same
order* — scenario tests for each policy dimension, then a randomized
property sweep (SURVEY.md section 4: "serial result ≡ vectorized result
on identical snapshots").
"""

import random

import pytest

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import (
    Affinity,
    NodeSelectorTerm,
    PodPhase,
    Taint,
    Toleration,
)
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

# A reduced envelope without drf/proportion (exercises the kernel's
# static-key compile variant).
TIERS_YAML = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""

# The reference's *default* conf (util.go:31-42): drf job shares,
# proportion queue shares + overused gate fold into the kernel loop.
DEFAULT_TIERS_YAML = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def tiers(yaml_text=TIERS_YAML):
    return parse_scheduler_conf(yaml_text).tiers


def run_and_capture(action_name, cluster, tiers_yaml=TIERS_YAML):
    """Run one action; return ({task_uid: (status, node)}, binds)."""
    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers(tiers_yaml))
    get_action(action_name).execute(ssn)
    state = {}
    for job in ssn.jobs.values():
        for tasks in job.task_status_index.values():
            for t in tasks.values():
                state[t.uid] = (t.status, t.node_name)
    close_session(ssn)
    return state, dict(cache.binder.binds)


def assert_equivalent(make_cluster, tiers_yaml=TIERS_YAML):
    """Build the cluster twice (identical), run serial + XLA, compare."""
    s_state, s_binds = run_and_capture("allocate", make_cluster(), tiers_yaml)
    x_state, x_binds = run_and_capture("xla_allocate", make_cluster(), tiers_yaml)
    assert x_state == s_state
    assert x_binds == s_binds


# -- scenario tests ----------------------------------------------------------


def test_gang_atomic_binds():
    def mk():
        pods = [
            build_pod(name=f"p{i}", group_name="pg1", req=build_resource_list(cpu=1, memory="512Mi"))
            for i in range(3)
        ]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=1, memory="1Gi", pods=10))
            for i in range(3)
        ]
        return build_cluster(pods, nodes, [build_pod_group("pg1", min_member=3)], [build_queue("default")])

    s, binds = run_and_capture("xla_allocate", mk())
    assert len(binds) == 3
    assert_equivalent(mk)


def test_gang_starved_holds_resources_without_bind():
    """minMember=4 with 3 slots: 3 tasks sit Allocated, nothing binds."""

    def mk():
        pods = [
            build_pod(name=f"p{i}", group_name="pg1", req=build_resource_list(cpu=1, memory="512Mi"))
            for i in range(4)
        ]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=1, memory="1Gi", pods=10))
            for i in range(3)
        ]
        return build_cluster(pods, nodes, [build_pod_group("pg1", min_member=4)], [build_queue("default")])

    s, binds = run_and_capture("xla_allocate", mk())
    assert binds == {}
    assert sum(1 for st, _ in s.values() if st == TaskStatus.ALLOCATED) == 3
    assert_equivalent(mk)


def test_priority_order_and_spread():
    """Higher-priority job drains first; least-requested spreads load."""

    def mk():
        pods = [
            build_pod(name=f"lo{i}", group_name="lo", req=build_resource_list(cpu=1, memory="512Mi"), priority=1)
            for i in range(2)
        ] + [
            build_pod(name=f"hi{i}", group_name="hi", req=build_resource_list(cpu=1, memory="512Mi"), priority=9)
            for i in range(2)
        ]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=2, memory="2Gi", pods=10))
            for i in range(2)
        ]
        return build_cluster(
            pods,
            nodes,
            [build_pod_group("lo", min_member=1), build_pod_group("hi", min_member=1)],
            [build_queue("default")],
        )

    assert_equivalent(mk)


def test_node_selector_and_taints():
    def mk():
        sel = build_pod(
            name="sel",
            group_name="pg1",
            req=build_resource_list(cpu=1, memory="256Mi"),
            node_selector={"zone": "a"},
        )
        tol = build_pod(name="tol", group_name="pg1", req=build_resource_list(cpu=1, memory="256Mi"))
        tol.tolerations = [Toleration(key="dedicated", operator="Equal", value="infra", effect="NoSchedule")]
        plain = build_pod(name="plain", group_name="pg1", req=build_resource_list(cpu=1, memory="256Mi"))
        n_zone = build_node("zone-a", build_resource_list(cpu=1, memory="1Gi", pods=10), labels={"zone": "a"})
        n_taint = build_node("tainted", build_resource_list(cpu=8, memory="8Gi", pods=10))
        n_taint.taints = [Taint(key="dedicated", value="infra", effect="NoSchedule")]
        n_plain = build_node("plain", build_resource_list(cpu=1, memory="1Gi", pods=10))
        return build_cluster(
            [sel, tol, plain],
            [n_zone, n_taint, n_plain],
            [build_pod_group("pg1", min_member=1)],
            [build_queue("default")],
        )

    s, _ = run_and_capture("xla_allocate", mk())
    assert s["default-sel"][1] == "zone-a"
    assert s["default-tol"][1] == "tainted"
    assert_equivalent(mk)


def test_pipeline_onto_releasing():
    """A task that fits only a terminating pod's resources pipelines."""

    def mk():
        leaving = build_pod(
            name="leaving",
            node_name="n0",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=1, memory="1Gi"),
        )
        leaving.metadata.deletion_timestamp = 1.0
        pending = build_pod(name="pending", group_name="pg1", req=build_resource_list(cpu=1, memory="1Gi"))
        nodes = [build_node("n0", build_resource_list(cpu=1, memory="1Gi", pods=10))]
        return build_cluster(
            [leaving, pending],
            nodes,
            [build_pod_group("pg1", min_member=1)],
            [build_queue("default")],
        )

    s, binds = run_and_capture("xla_allocate", mk())
    assert s["default-pending"] == (TaskStatus.PIPELINED, "n0")
    assert binds == {}
    assert_equivalent(mk)


def test_multi_queue_round_robin():
    def mk():
        pods = []
        pgs = []
        for q in ("qa", "qb"):
            for j in range(2):
                name = f"{q}-j{j}"
                pgs.append(build_pod_group(name, queue=q, min_member=1))
                pods.extend(
                    build_pod(name=f"{name}-p{i}", group_name=name, req=build_resource_list(cpu=1, memory="256Mi"))
                    for i in range(2)
                )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=2, memory="2Gi", pods=10))
            for i in range(3)
        ]
        return build_cluster(pods, nodes, pgs, [build_queue("qa"), build_queue("qb")])

    assert_equivalent(mk)


def test_host_ports_conflict():
    def mk():
        pods = [
            build_pod(name=f"web{i}", group_name="pg1", req=build_resource_list(cpu=1, memory="128Mi"))
            for i in range(3)
        ]
        for p in pods:
            p.containers[0].ports = [8080]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=10))
            for i in range(2)
        ]
        return build_cluster(pods, nodes, [build_pod_group("pg1", min_member=1)], [build_queue("default")])

    s, _ = run_and_capture("xla_allocate", mk())
    placed = [n for st, n in s.values() if n]
    assert len(placed) == 2 and len(set(placed)) == 2  # one per node, third unplaced
    assert_equivalent(mk)


def test_preferred_node_affinity_score():
    def mk():
        pod = build_pod(name="aff", group_name="pg1", req=build_resource_list(cpu=1, memory="128Mi"))
        pod.affinity = Affinity(
            node_affinity_preferred=[(20, NodeSelectorTerm(key="disk", operator="In", values=["ssd"]))]
        )
        nodes = [
            build_node("big", build_resource_list(cpu=16, memory="16Gi", pods=10)),
            build_node("ssd", build_resource_list(cpu=2, memory="2Gi", pods=10), labels={"disk": "ssd"}),
        ]
        return build_cluster([pod], nodes, [build_pod_group("pg1", min_member=1)], [build_queue("default")])

    s, _ = run_and_capture("xla_allocate", mk())
    assert s["default-aff"][1] == "ssd"
    assert_equivalent(mk)


def test_pod_affinity_falls_back_to_serial():
    """Required pod-affinity is host-only: xla_allocate must fall back,
    producing the serial result (not an unscheduled task)."""

    def mk():
        anchor = build_pod(
            name="anchor",
            node_name="n0",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=1, memory="128Mi"),
            labels={"app": "db"},
        )
        follower = build_pod(name="follower", group_name="pg1", req=build_resource_list(cpu=1, memory="128Mi"))
        from kube_batch_tpu.apis.types import PodAffinityTerm

        follower.affinity = Affinity(
            pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
        )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=4, memory="4Gi", pods=10))
            for i in range(3)
        ]
        return build_cluster(
            [anchor, follower], nodes, [build_pod_group("pg1", min_member=1)], [build_queue("default")]
        )

    s, _ = run_and_capture("xla_allocate", mk())
    assert s["default-follower"][1] == "n0"
    assert_equivalent(mk)


def test_out_of_envelope_conf_falls_back():
    """Confs the kernel does not model exactly (here: no priority plugin,
    so serial ordering is creation/uid only) must produce the serial
    result via fallback, not a silently different placement."""
    no_priority_yaml = """
tiers:
- plugins:
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""
    t = parse_scheduler_conf(no_priority_yaml).tiers

    def mk():
        # Two tasks in two jobs; priority says hi first, creation says lo
        # first — an envelope bug would schedule hi onto the single slot.
        lo = build_pod(name="lo", group_name="lo", req=build_resource_list(cpu=1, memory="512Mi"), priority=1)
        lo.metadata.creation_timestamp = 0.0
        hi = build_pod(name="hi", group_name="hi", req=build_resource_list(cpu=1, memory="512Mi"), priority=9)
        hi.metadata.creation_timestamp = 5.0
        pg_lo = build_pod_group("lo", min_member=1)
        pg_lo.metadata.creation_timestamp = 0.0
        pg_hi = build_pod_group("hi", min_member=1)
        pg_hi.metadata.creation_timestamp = 5.0
        nodes = [build_node("n0", build_resource_list(cpu=1, memory="1Gi", pods=10))]
        return build_cluster([lo, hi], nodes, [pg_lo, pg_hi], [build_queue("default")])

    def run(action):
        cache = FakeCache(mk())
        ssn = open_session(cache, t)
        get_action(action).execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds)

    s_binds = run("allocate")
    x_binds = run("xla_allocate")
    assert x_binds == s_binds == {"default/lo": "n0"}


# -- randomized property sweep ----------------------------------------------


def gen_cluster(seed: int):
    """Random cluster on the milli/MiB grid: gang jobs, priorities,
    selectors, taints/tolerations, preloaded running + releasing pods,
    multiple queues, and (on some seeds) scalar accelerator resources —
    the dims that drive the drf/proportion Go nil-scalar-map parity
    bits and the scalar feasibility gates (resource_info.go:255-278)."""
    from kube_batch_tpu.models import GPU

    rng = random.Random(seed)
    n_queues = rng.randint(1, 3)
    queues = [build_queue(f"q{i}", weight=rng.randint(1, 3)) for i in range(n_queues)]
    for i, q in enumerate(queues):
        q.metadata.creation_timestamp = float(i)

    # a third of the clusters carry an accelerator scalar on part of
    # the fleet, with some pods requesting it
    with_scalars = rng.random() < 0.35

    nodes = []
    for i in range(rng.randint(3, 10)):
        labels = {}
        if rng.random() < 0.4:
            labels["zone"] = rng.choice(["a", "b"])
        rl = build_resource_list(
            cpu=rng.randint(1, 8),
            memory=f"{rng.choice([1024, 2048, 4096, 8192])}Mi",
            pods=rng.randint(3, 12),
        )
        if with_scalars and rng.random() < 0.6:
            rl[GPU] = float(rng.choice([1, 2, 4]))
        node = build_node(
            f"n{i:02d}",
            rl,
            labels=labels,
        )
        if rng.random() < 0.15:
            node.taints = [Taint(key="dedicated", value="infra", effect="NoSchedule")]
        if rng.random() < 0.1:
            node.unschedulable = True
        nodes.append(node)

    pods, pgs = [], []
    for j in range(rng.randint(1, 7)):
        name = f"job{j}"
        n_tasks = rng.randint(1, 5)
        min_member = rng.randint(1, n_tasks + (1 if rng.random() < 0.2 else 0))
        queue = rng.choice(queues).name
        pg = build_pod_group(name, queue=queue, min_member=min_member)
        pg.metadata.creation_timestamp = float(rng.randint(0, 3))
        pgs.append(pg)
        prio = rng.choice([None, 1, 5, 9])
        for t in range(n_tasks):
            req = build_resource_list(
                cpu=f"{rng.randint(1, 16) * 250}m",
                memory=f"{rng.choice([128, 256, 512, 1024, 2048])}Mi",
            )
            if with_scalars and rng.random() < 0.4:
                req[GPU] = float(rng.choice([1, 2]))
            pod = build_pod(
                name=f"{name}-t{t}",
                group_name=name,
                req=req,
                priority=prio if rng.random() < 0.8 else rng.choice([1, 5, 9]),
            )
            pod.metadata.creation_timestamp = float(rng.randint(0, 3))
            if rng.random() < 0.2:
                pod.node_selector = {"zone": rng.choice(["a", "b"])}
            if rng.random() < 0.15:
                pod.tolerations = [
                    Toleration(key="dedicated", operator="Equal", value="infra", effect="NoSchedule")
                ]
            if rng.random() < 0.1:
                pod.affinity = Affinity(
                    node_affinity_preferred=[
                        (rng.randint(1, 10), NodeSelectorTerm(key="zone", operator="In", values=["a"]))
                    ]
                )
            pods.append(pod)

    # Preloaded running / releasing pods occupy nodes (only where they fit).
    headroom = {
        n.name: [n.allocatable.get("cpu", 0.0) * 1000.0, n.allocatable.get("memory", 0.0)]
        for n in nodes
    }
    for r in range(rng.randint(0, 6)):
        node = rng.choice(nodes)
        cpu_m = rng.randint(1, 4) * 250
        mem_mi = rng.choice([128, 256, 512])
        room = headroom[node.name]
        if room[0] < cpu_m or room[1] < mem_mi * 1024 * 1024:
            continue
        room[0] -= cpu_m
        room[1] -= mem_mi * 1024 * 1024
        pod = build_pod(
            name=f"resident{r}",
            node_name=node.name,
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=f"{cpu_m}m", memory=f"{mem_mi}Mi"),
        )
        if rng.random() < 0.3:
            pod.metadata.deletion_timestamp = 1.0
        pods.append(pod)

    return build_cluster(pods, nodes, pgs, queues)


@pytest.mark.parametrize("batch", range(2))
def test_property_serial_equals_xla(batch):
    """Random snapshots under the reduced (no-drf/proportion) envelope:
    serial allocate ≡ xla_allocate, assignment for assignment (VERDICT
    round-1 item 3's done-criterion)."""
    for seed in range(batch * 24, (batch + 1) * 24):
        s_state, s_binds = run_and_capture("allocate", gen_cluster(seed))
        x_state, x_binds = run_and_capture("xla_allocate", gen_cluster(seed))
        assert x_state == s_state, f"seed {seed}: state diverged"
        assert x_binds == s_binds, f"seed {seed}: binds diverged"


@pytest.mark.parametrize("batch", range(5))
def test_property_default_conf_serial_equals_xla(batch):
    """≥100 random snapshots under the reference's *default* conf
    (drf + proportion active): the kernel's in-loop share/overused state
    must match the serial plugins decision for decision (VERDICT r2
    item 2's done-criterion)."""
    for seed in range(batch * 24, (batch + 1) * 24):
        s_state, s_binds = run_and_capture(
            "allocate", gen_cluster(seed), DEFAULT_TIERS_YAML
        )
        x_state, x_binds = run_and_capture(
            "xla_allocate", gen_cluster(seed), DEFAULT_TIERS_YAML
        )
        assert x_state == s_state, f"seed {seed}: state diverged"
        assert x_binds == s_binds, f"seed {seed}: binds diverged"


def test_proportion_overused_queue_dropped():
    """A queue past its deserved share is skipped for the cycle
    (proportion.go:188-199): its second job must not schedule while the
    underserved queue drains fully — and serial ≡ XLA on the outcome."""

    def mk():
        pods, pgs = [], []
        # qa: tiny weight, big appetite; qb: big weight.
        for q, njobs in (("qa", 3), ("qb", 3)):
            for j in range(njobs):
                name = f"{q}-j{j}"
                pgs.append(build_pod_group(name, queue=q, min_member=1))
                pods.extend(
                    build_pod(
                        name=f"{name}-p{i}",
                        group_name=name,
                        req=build_resource_list(cpu=1, memory="1Gi"),
                    )
                    for i in range(2)
                )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=2, memory="2Gi", pods=10))
            for i in range(3)
        ]
        qa = build_queue("qa", weight=1)
        qb = build_queue("qb", weight=5)
        qa.metadata.creation_timestamp = 0.0
        qb.metadata.creation_timestamp = 1.0
        return build_cluster(pods, nodes, pgs, [qa, qb])

    assert_equivalent(mk, DEFAULT_TIERS_YAML)


def test_drf_share_orders_jobs():
    """With drf active, a job that already holds resources yields to the
    zero-share job at equal priority — serial ≡ XLA."""

    def mk():
        fat_resident = build_pod(
            name="fat-r0",
            group_name="fat",
            node_name="n0",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=2, memory="2Gi"),
        )
        pods = [fat_resident] + [
            build_pod(
                name=f"fat-p{i}",
                group_name="fat",
                req=build_resource_list(cpu=1, memory="1Gi"),
            )
            for i in range(2)
        ] + [
            build_pod(
                name=f"thin-p{i}",
                group_name="thin",
                req=build_resource_list(cpu=1, memory="1Gi"),
            )
            for i in range(2)
        ]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=4, memory="4Gi", pods=10))
            for i in range(2)
        ]
        pg_fat = build_pod_group("fat", min_member=1)
        pg_fat.metadata.creation_timestamp = 0.0
        pg_thin = build_pod_group("thin", min_member=1)
        pg_thin.metadata.creation_timestamp = 1.0
        return build_cluster(pods, nodes, [pg_fat, pg_thin], [build_queue("default")])

    assert_equivalent(mk, DEFAULT_TIERS_YAML)


def test_small_snapshot_routes_serial(monkeypatch):
    """Below the device size floor the action runs the serial allocate
    (same result, no device round trip); 0 forces the device path (what
    the rest of this suite relies on via conftest)."""
    import kube_batch_tpu.actions.xla_allocate as XA

    def mk():
        pods = [
            build_pod(name=f"p{i}", group_name="g", req=build_resource_list(cpu=1, memory="512Mi"))
            for i in range(3)
        ]
        nodes = [build_node(f"n{i}", build_resource_list(cpu=4, memory="4Gi", pods=10)) for i in range(2)]
        return build_cluster(pods, nodes, [build_pod_group("g", min_member=3)], [build_queue("default")])

    monkeypatch.setenv("KBT_MIN_DEVICE_PAIRS", "32768")
    action = XA.XlaAllocateAction()
    cache = FakeCache(mk())
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    action.execute(ssn)
    routed_binds = dict(cache.binder.binds)
    assert "serial_routed_s" in action.last_timings  # serial path taken
    close_session(ssn)

    monkeypatch.setenv("KBT_MIN_DEVICE_PAIRS", "0")
    action = XA.XlaAllocateAction()
    cache = FakeCache(mk())
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    action.execute(ssn)
    device_binds = dict(cache.binder.binds)
    assert "solve_s" in action.last_timings  # device path taken
    close_session(ssn)

    assert routed_binds == device_binds and len(routed_binds) == 3


def test_conf_selected_mesh_skips_size_floor(monkeypatch):
    """An explicit mesh request is a statement of intent: the size floor
    must not reroute it (the multichip dryrun depends on this)."""
    import kube_batch_tpu.actions.xla_allocate as XA

    monkeypatch.setenv("KBT_MIN_DEVICE_PAIRS", str(10**9))
    monkeypatch.setenv("KBT_MESH", "cpu:2")
    pods = [
        build_pod(name=f"p{i}", group_name="g", req=build_resource_list(cpu=1, memory="512Mi"))
        for i in range(4)
    ]
    nodes = [build_node(f"n{i}", build_resource_list(cpu=4, memory="4Gi", pods=10)) for i in range(2)]
    cluster = build_cluster(pods, nodes, [build_pod_group("g", min_member=4)], [build_queue("default")])
    action = XA.XlaAllocateAction()
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    action.execute(ssn)
    assert action.last_mesh_size == 2  # mesh engaged despite the floor
    assert "serial_routed_s" not in action.last_timings
    assert len(cache.binder.binds) == 4
    close_session(ssn)


def test_task_latency_histogram_stamped_from_solve_completion():
    """VERDICT r4 item 9: the bulk replay populates the task latency
    histogram with PER-TASK stamps taken at each task's solve-segment
    completion (decided_at), matching the reference's per-task dispatch
    stamping (metrics.go:66-72) — not one batch timestamp."""
    import time

    from kube_batch_tpu import metrics

    before = metrics.task_scheduling_latency.snapshot()
    t_create = time.time() - 5.0  # pods created 5s ago
    pods = [
        build_pod(name=f"lat{i}", group_name="glat",
                  req=build_resource_list(cpu=1, memory="512Mi"))
        for i in range(6)
    ]
    for p in pods:
        p.metadata.creation_timestamp = t_create
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=10))
        for i in range(2)
    ]
    cluster = build_cluster(
        pods, nodes, [build_pod_group("glat", min_member=6)], [build_queue("default")]
    )
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    get_action("xla_allocate").execute(ssn)
    close_session(ssn)
    assert len(cache.binder.binds) == 6
    snap = metrics.task_scheduling_latency.snapshot()
    d_count = snap["count"] - before["count"]
    d_sum = snap["sum"] - before["sum"]
    assert d_count == 6
    # each stamp ~5s (creation 5s ago, decided moments later) — a wrong
    # timestamp source (0, or absolute wall time) falls outside the band
    assert 4.0 <= d_sum / 6 <= 60.0, d_sum / 6


def test_native_dispatch_engages_after_glog_line(monkeypatch):
    """ADVICE r5 (medium): emitting ONE glog line initializes the package
    handler, which sets the parent 'kube_batch_tpu' logger to DEBUG; the
    old `log.isEnabledFor(DEBUG)` gate then read True forever at -v 0 and
    permanently disabled the native bulk_dispatch fast path. The gate is
    package verbosity now — the native path must engage regardless of
    handler initialization."""
    import kube_batch_tpu.actions.xla_allocate as XA
    from kube_batch_tpu import log as glog

    # the handler-initializing line (leader-election startup chatter,
    # any errorf — one is enough)
    glog.infof("startup chatter: handler now initialized")
    assert glog.get_verbosity() < 4

    calls = {"dispatch": 0}

    class FakeNative:
        """bulk_dispatch with the real semantics (gang buckets move
        wholesale ALLOCATED -> BINDING, tasks in dispatch order); every
        other native entry point absent, so the replay's remaining steps
        take their Python twins."""

        def bulk_dispatch(self, jobs, mask, allocated_status, binding_status):
            calls["dispatch"] += 1
            out = []
            for i, job in enumerate(jobs):
                if not mask[i]:
                    continue
                allocated = job.task_status_index.pop(allocated_status, None)
                if not allocated:
                    continue
                for t in allocated.values():
                    t.status = binding_status
                binding = job.task_status_index.setdefault(binding_status, {})
                binding.update(allocated)
                out.extend(allocated.values())
            return out

    monkeypatch.setattr(XA, "_native", FakeNative())
    pods = [
        build_pod(name=f"p{i}", group_name="g", req=build_resource_list(cpu=1, memory="512Mi"))
        for i in range(4)
    ]
    nodes = [build_node(f"n{i}", build_resource_list(cpu=4, memory="4Gi", pods=10)) for i in range(2)]
    cluster = build_cluster(pods, nodes, [build_pod_group("g", min_member=4)], [build_queue("default")])
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    XA.XlaAllocateAction().execute(ssn)
    close_session(ssn)
    assert len(cache.binder.binds) == 4
    assert calls["dispatch"] == 1, "native bulk_dispatch fast path did not engage"
