"""Sharded multi-scheduler federation (ISSUE 10): the store's
conditional-write transactions, the ``/backend/v1/`` wire path
(LoopbackBackend against a real SchedulerServer), shard-key helpers,
lease edge cases under real concurrency, and the conflict chaos drill —
``store.conflict`` + ``federation.partition`` armed, two schedulers on
one store, one of them killed mid-conflict, zero lost and zero
duplicate binds after reconciliation.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import zlib

import pytest

from kube_batch_tpu import faults, metrics
from kube_batch_tpu.api.job_info import job_key
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis import wire
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.cache import (
    BackendPartitioned,
    ClusterStore,
    EventHandler,
    LoopbackBackend,
    SchedulerCache,
    StaleWrite,
)
from kube_batch_tpu.cache.cache import StoreBinder
from kube_batch_tpu.cache.store import LEASES, NODES, PODS, POD_GROUPS, QUEUES
from kube_batch_tpu.faults.mutation_detector import MutationDetector
from kube_batch_tpu.federation import (
    SHARD_KEYS,
    FederatedCache,
    enabled,
    fsck,
    parse_shard_spec,
    shard_index,
    shard_key_mode,
    shard_key_of,
)
from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)
from kube_batch_tpu.utils.locking import LockOrderWitness


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def seed_store(store, nodes=1, cpu=16, gangs=(), members=3):
    if store.get(QUEUES, "default") is None:  # the server pre-seeds one
        store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(
                f"n{i}", build_resource_list(cpu=cpu, memory=f"{cpu}Gi", pods=64)
            )
        )
    for g in gangs:
        store.create_pod_group(build_pod_group(g, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{g}-p{m}", group_name=g,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def bind_gang(cache, gang, node="n0"):
    """Dispatch every pending task of ``gang`` as one bulk bind (the
    federation unit: one gang = one optimistic transaction)."""
    uid = job_key("default", gang)
    with cache._mutex:
        job = cache.jobs.get(uid)
        pending = (
            list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
            if job is not None
            else []
        )
    assert pending, f"gang {gang} has no pending tasks in this cache"
    cache.bind_many([(t, node) for t in pending])


def count_bind_events(store):
    """pod key -> number of unbound->bound transitions (the
    duplicate-bind detector of the acceptance criterion)."""
    counts: dict[str, int] = {}
    lock = threading.Lock()

    def on_update(old, new):
        if not old.node_name and new.node_name:
            with lock:
                key = f"{new.namespace}/{new.name}"
                counts[key] = counts.get(key, 0) + 1

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    return counts


# -- conditional store writes ------------------------------------------------


def test_conditional_bind_commits_and_bumps_placement_version():
    store = ClusterStore()
    seed_store(store, gangs=("g0",), members=2)
    v = store.version
    assert store.placement_version("n0") == 0
    applied = store.conditional_bind_many([("default", "g0-p0", "n0")], v)
    assert [p.name for p in applied] == ["g0-p0"]
    assert store.get_pod("default", "g0-p0").node_name == "n0"
    assert store.placement_version("n0") > v
    assert store.version > v


def test_stale_node_conflict_is_typed():
    store = ClusterStore()
    seed_store(store, gangs=("g0",), members=2)
    v = store.version  # both schedulers snapshot here
    store.conditional_bind_many([("default", "g0-p0", "n0")], v)
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many([("default", "g0-p1", "n0")], v)
    e = ei.value
    assert (e.kind, e.key, e.reason) == (NODES, "n0", "stale_node")
    assert e.expected == v and e.actual > v
    assert f"stale write on {NODES} 'n0': stale_node" in str(e)
    # the loser's pod is untouched — a rejected gang needs no rollback
    assert store.get_pod("default", "g0-p1").node_name == ""
    # refresh-and-retry wins (the _do_bind_gang loop's contract)
    store.conditional_bind_many([("default", "g0-p1", "n0")], store.version)
    assert store.get_pod("default", "g0-p1").node_name == "n0"


def test_same_host_rebind_is_idempotent_skip_not_conflict():
    """The journal re-dispatch case: re-sending a landed bind (even with
    an ancient snapshot version) must skip, not conflict."""
    store = ClusterStore()
    seed_store(store, gangs=("g0",), members=1)
    v = store.version
    store.conditional_bind_many([("default", "g0-p0", "n0")], v)
    applied = store.conditional_bind_many([("default", "g0-p0", "n0")], v)
    assert applied == []


def test_already_bound_elsewhere_missing_and_no_node_reject():
    store = ClusterStore()
    seed_store(store, nodes=2, gangs=("g0",), members=1)
    store.conditional_bind_many([("default", "g0-p0", "n0")], store.version)
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many([("default", "g0-p0", "n1")], store.version)
    assert ei.value.reason == "already_bound"
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many([("default", "ghost", "n0")], store.version)
    assert ei.value.reason == "missing"
    store.create_pod(build_pod(name="solo", req=build_resource_list(cpu=1)))
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many([("default", "solo", "n9")], store.version)
    assert ei.value.reason == "no_node"


def test_capacity_rejection_is_all_or_nothing():
    """Store-side admission: a gang that no longer fits rejects whole —
    no member is applied, the store version does not move."""
    store = ClusterStore()
    seed_store(store, cpu=2, gangs=("g0",), members=3)  # 3x1cpu onto 2cpu
    v = store.version
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many(
            [("default", f"g0-p{m}", "n0") for m in range(3)], v
        )
    assert ei.value.reason == "capacity"
    assert store.version == v
    assert all(not p.node_name for p in store.list(PODS))


def test_conditional_evict_stale_then_fresh_then_idempotent():
    store = ClusterStore()
    seed_store(store, gangs=("g0",), members=2)
    stale = store.version
    store.conditional_bind_many([("default", "g0-p0", "n0")], stale)
    # the preemption plan was solved before that placement: rejected
    with pytest.raises(StaleWrite) as ei:
        store.conditional_evict("default", "g0-p0", stale)
    assert ei.value.reason == "stale_node"
    assert store.conditional_evict("default", "g0-p0", store.version) is not None
    assert store.get_pod("default", "g0-p0") is None
    # journal re-dispatch of a landed evict: idempotent None
    assert store.conditional_evict("default", "g0-p0", store.version) is None


def test_store_conflict_fault_injects_typed_conflict():
    store = ClusterStore()
    seed_store(store, gangs=("g0",), members=1)
    faults.registry.arm("store.conflict", count=1)
    with pytest.raises(StaleWrite) as ei:
        store.conditional_bind_many([("default", "g0-p0", "n0")], store.version)
    assert ei.value.reason == "injected"
    # count exhausted: the retry lands
    store.conditional_bind_many([("default", "g0-p0", "n0")], store.version)
    assert store.get_pod("default", "g0-p0").node_name == "n0"


# -- wire codec --------------------------------------------------------------


def test_wire_codec_round_trips_through_json():
    pod = build_pod(
        name="w0", group_name="gw", req=build_resource_list(cpu=2, memory="1Gi"),
        labels={"tier": "batch"}, node_name="n3", phase=PodPhase.RUNNING,
    )
    node = build_node("n3", build_resource_list(cpu=8, memory="8Gi", pods=16),
                      labels={"zone": "a"})
    pg = build_pod_group("gw", min_member=4)
    q = build_queue("default", weight=3)
    for kind, obj in ((PODS, pod), (NODES, node), (POD_GROUPS, pg), (QUEUES, q)):
        data = json.loads(json.dumps(wire.encode_kind(kind, obj)))
        assert wire.decode_kind(kind, data) == obj


# -- shard keys --------------------------------------------------------------


def test_parse_shard_spec():
    assert parse_shard_spec("1/4") == (1, 4)
    assert parse_shard_spec(" 0/1 ") == (0, 1)
    assert parse_shard_spec("1") == (0, 1)  # bare flag: no partition
    for bad in ("4/4", "-1/2", "a/b", "1/0"):
        with pytest.raises(ValueError):
            parse_shard_spec(bad)


def test_shard_index_is_crc32_stable():
    # hash() is per-process salted; the bucket must be crc32 so every
    # scheduler process agrees on the partition
    assert shard_index("default/ga", 4) == zlib.crc32(b"default/ga") % 4
    assert shard_index("anything", 1) == 0


def test_all_shard_key_modes_are_gang_stable():
    store = ClusterStore()
    store.create_queue(build_queue("qx"))
    store.create_pod_group(build_pod_group("g1", queue="qx", min_member=2))
    pods = [build_pod(name=f"g1-p{m}", group_name="g1") for m in range(3)]
    for mode in SHARD_KEYS:
        keys = {shard_key_of(p, store, mode) for p in pods}
        assert len(keys) == 1, f"mode {mode} split a gang: {keys}"
    assert shard_key_of(pods[0], store, "queue") == "qx"
    assert shard_key_of(pods[0], store, "namespace") == "default"
    # a pod whose group has not arrived falls back to its gang key
    orphan = build_pod(name="solo", group_name="never-created")
    assert shard_key_of(orphan, store, "queue") == job_key(
        "default", "never-created"
    )


def test_federated_cache_filter_shards_only_unbound_pending():
    store = ClusterStore()
    seed_store(store)
    cache = FederatedCache(store, shard=0, shards=2, shard_key="gang")
    # "ga" and "gm" land in opposite crc32 buckets; pick whichever is
    # shard 0 as "mine" so the test is robust to bucket reassignment
    p_ga = build_pod(name="mine", group_name="ga")
    p_gm = build_pod(name="other", group_name="gm")
    assert shard_index(job_key("default", "ga"), 2) != shard_index(
        job_key("default", "gm"), 2
    )
    mine, other = (
        (p_ga, p_gm)
        if shard_index(job_key("default", "ga"), 2) == 0
        else (p_gm, p_ga)
    )
    assert cache._pod_filter(mine)
    assert not cache._pod_filter(other)
    # the other shard's pod becomes visible the moment it holds capacity
    # (bound but still phase-Pending) — the conflict-livelock guard
    assert cache._pod_filter(dataclasses.replace(other, node_name="n0"))
    assert cache._pod_filter(dataclasses.replace(other, phase=PodPhase.RUNNING,
                                                 node_name="n0"))
    with pytest.raises(ValueError):
        FederatedCache(store, shard=2, shards=2)
    with pytest.raises(ValueError):
        FederatedCache(store, shard=0, shards=2, shard_key="bogus")


def test_env_surface(monkeypatch):
    monkeypatch.delenv("KBT_FEDERATION", raising=False)
    assert not enabled()
    monkeypatch.setenv("KBT_FEDERATION", "0")
    assert not enabled()
    monkeypatch.setenv("KBT_FEDERATION", "1/2")
    assert enabled()
    assert SchedulerCache(ClusterStore())._conditional_binds
    monkeypatch.setenv("KBT_SHARD_KEY", "gang")
    assert shard_key_mode() == "gang"
    monkeypatch.setenv("KBT_SHARD_KEY", "bogus")
    assert shard_key_mode() == "queue"  # loud fallback, never a crash
    monkeypatch.setenv("KBT_CONFLICT_MAX_RETRIES", "7")
    assert SchedulerCache(ClusterStore())._conflict_max_retries == 7
    monkeypatch.setenv("KBT_CONFLICT_MAX_RETRIES", "lots")
    assert SchedulerCache(ClusterStore())._conflict_max_retries == 3


def test_federation_metrics_registered_in_exposition():
    metrics.register_federation_conflict("clean")
    metrics.register_bind_retry()
    metrics.observe_store_backend_rtt("list", 0.001)
    text = metrics.render_prometheus_text()
    for name in (
        "federation_conflicts_total",
        "bind_retries_total",
        "store_backend_rtt_seconds",
    ):
        assert name in text


# -- lease edge cases (satellite: leader-election arbiter) -------------------


def test_lease_concurrent_two_identities_witnessed():
    """Two identities hammer try_acquire/release concurrently under a
    LockOrderWitness. Safety: the holder NEVER transfers directly
    between two live identities — every handoff passes through the
    released sentinel (duration is 30s, so expiry can't arbitrate)."""
    store = ClusterStore()
    witness = LockOrderWitness()
    store._lock = witness.wrap("store._lock", store._lock)
    store._dispatch_lock = witness.wrap(
        "store._dispatch_lock", store._dispatch_lock
    )
    transitions: list[tuple[str, str]] = []
    store.add_event_handler(
        LEASES,
        EventHandler(
            on_update=lambda old, new: transitions.append(
                (old.holder_identity, new.holder_identity)
            )
        ),
    )
    acquired = {"a": 0, "b": 0}
    errors: list[BaseException] = []

    def worker(ident: str) -> None:
        try:
            for _ in range(40):
                lease = store.try_acquire_lease("kb-fed", ident, 30.0)
                if lease.holder_identity == ident:
                    acquired[ident] += 1
                    store.release_lease("kb-fed", ident)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert witness.violations == []
    assert sum(acquired.values()) >= 1
    final = store.get(LEASES, "kb-fed")
    assert final.holder_identity in ("", "a", "b")
    for old, new in transitions:
        if old and new:
            assert old == new, f"live steal {old}->{new} without release"


def test_lease_released_sentinel_lets_waiter_take_over_immediately():
    store = ClusterStore()
    a = store.try_acquire_lease("kb", "a", 15.0, now=100.0)
    assert (a.holder_identity, a.lease_transitions) == ("a", 0)
    # fresh and held by a: b's attempt mutates nothing, not even version
    v = store.version
    assert store.try_acquire_lease("kb", "b", 15.0, now=101.0).holder_identity == "a"
    assert store.version == v
    released = store.release_lease("kb", "a")
    assert released.holder_identity == ""
    assert store.version == v + 1
    # third waiter: the "" sentinel is takeable NOW, well inside the
    # original 15s window — no expiry wait (ReleaseOnCancel behavior)
    c = store.try_acquire_lease("kb", "c", 15.0, now=102.0)
    assert (c.holder_identity, c.lease_transitions) == ("c", 1)
    assert c.acquire_time == 102.0
    # and c now holds it fresh against everyone else
    assert store.try_acquire_lease("kb", "b", 15.0, now=103.0).holder_identity == "c"


def test_lease_empty_identity_rejected_both_ways():
    store = ClusterStore()
    with pytest.raises(ValueError):
        store.try_acquire_lease("kb", "", 15.0)
    with pytest.raises(ValueError):
        store.release_lease("kb", "")


# -- LoopbackBackend over a live server --------------------------------------


@pytest.fixture()
def arbiter():
    """A real SchedulerServer acting as the store process: its own loop
    is idled by a scheduler name no workload pod carries."""
    srv = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    srv.start()
    try:
        yield srv
    finally:
        srv.stop()


def _backend_for(arbiter) -> LoopbackBackend:
    return LoopbackBackend(f"http://127.0.0.1:{arbiter.listen_port}")


def test_backend_list_watch_mirror(arbiter):
    seed_store(arbiter.store, gangs=("g0",), members=1)
    backend = _backend_for(arbiter)
    events: list[tuple] = []
    backend.add_event_handler(
        PODS,
        EventHandler(
            on_add=lambda obj: events.append(("add", obj.name)),
            on_update=lambda old, new: events.append(("update", new.name)),
            on_delete=lambda obj: events.append(("delete", obj.name)),
        ),
    )
    # subscription listed + replayed the current world, full fidelity
    assert events == [("add", "g0-p0")]
    assert backend.get_pod("default", "g0-p0") == arbiter.store.get_pod(
        "default", "g0-p0"
    )
    arbiter.store.create_pod(build_pod(name="late", req=build_resource_list(cpu=1)))
    arbiter.store.conditional_bind_many(
        [("default", "g0-p0", "n0")], arbiter.store.version
    )
    assert backend.pump() >= 2
    assert backend.get_pod("default", "g0-p0").node_name == "n0"
    assert {p.name for p in backend.list(PODS)} == {"g0-p0", "late"}
    arbiter.store.delete_pod("default", "late")
    backend.pump()
    assert ("delete", "late") in events
    assert backend.get_pod("default", "late") is None


def test_backend_conditional_writes_and_409_reconstruction(arbiter):
    seed_store(arbiter.store, gangs=("g0",), members=2)
    backend = _backend_for(arbiter)
    v = backend.version
    assert v == arbiter.store.version
    assert backend.conditional_bind_many([("default", "g0-p0", "n0")], v) == 1
    # the server's typed 409 comes back as the SAME StaleWrite the
    # in-process store raises — conflict dispatch is backend-agnostic
    with pytest.raises(StaleWrite) as ei:
        backend.conditional_bind_many([("default", "g0-p1", "n0")], v)
    e = ei.value
    assert (e.kind, e.key, e.reason) == (NODES, "n0", "stale_node")
    assert e.expected == v and e.actual > v
    assert backend.conditional_bind_many(
        [("default", "g0-p1", "n0")], backend.version
    ) == 1
    assert backend.conditional_evict(
        "default", "g0-p1", backend.version
    ) is True
    assert backend.conditional_evict(
        "default", "g0-p1", backend.version
    ) is False  # idempotent re-dispatch
    assert arbiter.store.get_pod("default", "g0-p1") is None


def test_backend_crud_writes_land_in_store(arbiter):
    seed_store(arbiter.store)
    backend = _backend_for(arbiter)
    backend.create_pod(build_pod(name="px", req=build_resource_list(cpu=1)))
    assert arbiter.store.get_pod("default", "px") is not None
    backend.update_pod(
        dataclasses.replace(arbiter.store.get_pod("default", "px"),
                            phase=PodPhase.RUNNING)
    )
    assert arbiter.store.get_pod("default", "px").phase == PodPhase.RUNNING
    backend.delete_pod("default", "px")
    assert arbiter.store.get_pod("default", "px") is None
    backend.create(POD_GROUPS, build_pod_group("gX", min_member=2))
    assert arbiter.store.get(POD_GROUPS, "default/gX").spec.min_member == 2
    backend.update_pod_group(build_pod_group("gX", min_member=5))
    assert arbiter.store.get(POD_GROUPS, "default/gX").spec.min_member == 5


def test_backend_watch_410_heals_by_relist(arbiter):
    seed_store(arbiter.store, gangs=("g0",), members=1)
    backend = _backend_for(arbiter)
    seen: list[str] = []
    backend.add_event_handler(
        PODS, EventHandler(on_add=lambda obj: seen.append(obj.name))
    )
    arbiter.store.create_pod(build_pod(name="during-gap",
                                       req=build_resource_list(cpu=1)))
    # watch.drop injects the 410-Gone contract on the next poll: the
    # backend must re-list and synthesize the diff — the pod created
    # behind its back arrives exactly once
    faults.registry.arm("watch.drop", count=1)
    assert backend.pump() >= 1
    assert seen.count("during-gap") == 1
    assert {p.name for p in backend.list(PODS)} == {
        p.name for p in arbiter.store.list(PODS)
    }
    # the healed cursor resumes the ordinary stream
    arbiter.store.create_pod(build_pod(name="after-heal",
                                       req=build_resource_list(cpu=1)))
    backend.pump()
    assert seen.count("after-heal") == 1


# -- the chaos drills --------------------------------------------------------


class _Killed(BaseException):
    """SIGKILL stand-in (BaseException: no retry ladder survives it)."""


class KillingBinder(StoreBinder):
    """Dies on its Nth conditional dispatch — with store.conflict armed
    for the first call, N=2 kills the scheduler exactly mid-conflict
    (after the loss, before the retry lands)."""

    def __init__(self, store, die_on_call: int) -> None:
        super().__init__(store)
        self.calls = 0
        self.die_on_call = die_on_call

    def bind_many_versioned(self, bindings, snapshot_version) -> None:
        self.calls += 1
        if self.calls >= self.die_on_call:
            raise _Killed()
        super().bind_many_versioned(bindings, snapshot_version)


@pytest.mark.chaos
def test_chaos_conflict_kill_mid_retry_then_reconcile(tmp_path):
    """THE acceptance drill: two federated schedulers on one store,
    store.conflict armed; scheduler B loses its optimistic dispatch and
    is killed on the conflict retry; B's journal holds the whole gang as
    orphans; takeover reconciliation re-drives it — zero lost binds,
    zero duplicate binds, fsck clean, mutation detector clean."""
    store = ClusterStore()
    seed_store(store, gangs=("ga", "gb"), members=3)
    bind_counts = count_bind_events(store)
    ja = WriteIntentJournal(str(tmp_path / "a.wal"))
    jb = WriteIntentJournal(str(tmp_path / "b.wal"))
    # each cache's shard is whichever bucket its gang hashes into, so
    # the drill stays valid if crc32's assignment ever changes
    cache_a = FederatedCache(
        store, shard=shard_index(job_key("default", "ga"), 2), shards=2,
        shard_key="gang", journal=ja,
    )
    cache_b = FederatedCache(
        store, shard=shard_index(job_key("default", "gb"), 2), shards=2,
        shard_key="gang", journal=jb, binder=KillingBinder(store, die_on_call=2),
    )
    cache_a.snapshot()
    cache_b.snapshot()  # both solved over the same store version
    bind_gang(cache_a, "ga")
    assert all(
        store.get_pod("default", f"ga-p{m}").node_name == "n0" for m in range(3)
    )
    retried0 = metrics.federation_conflicts.value({"outcome": "retried"})
    faults.registry.arm("store.conflict", count=1)
    with pytest.raises(_Killed):
        bind_gang(cache_b, "gb")
    # died mid-conflict: one retry was in flight, nothing of gb landed
    assert metrics.federation_conflicts.value({"outcome": "retried"}) == retried0 + 1
    assert all(not store.get_pod("default", f"gb-p{m}").node_name for m in range(3))
    orphans = WriteIntentJournal.replay(jb.path).orphans
    assert [(i.op, i.pod) for i in orphans] == [
        ("bind", f"default/gb-p{m}") for m in range(3)
    ]

    # takeover: fresh journal handle against the same WAL, reconcile
    # before any loop runs; store truth drives, mutation detector armed
    jb_standby = WriteIntentJournal(jb.path)
    det = MutationDetector(store)
    det.snapshot()
    report = reconcile_journal(jb_standby, store)
    assert det.violations() == []
    assert report.redispatched == 3 and report.rolled_back == 0
    assert all(
        store.get_pod("default", f"{g}-p{m}").node_name == "n0"
        for g in ("ga", "gb") for m in range(3)
    )
    assert sorted(bind_counts) == sorted(
        f"default/{g}-p{m}" for g in ("ga", "gb") for m in range(3)
    )
    assert all(n == 1 for n in bind_counts.values()), f"duplicates: {bind_counts}"
    assert fsck(store) == []
    assert WriteIntentJournal.replay(jb.path).orphans == []
    ja.close()
    jb.close()
    jb_standby.close()


@pytest.mark.chaos
def test_chaos_natural_conflict_loser_retries_and_wins(tmp_path):
    """No faults: two schedulers snapshot the same version and race onto
    one node — the second dispatch loses stale_node for real and wins
    its refresh-retry. Both gangs end bound exactly once."""
    store = ClusterStore()
    seed_store(store, gangs=("ga", "gb"), members=3)
    bind_counts = count_bind_events(store)
    cache_a = FederatedCache(
        store, shard=shard_index(job_key("default", "ga"), 2), shards=2,
        shard_key="gang",
    )
    cache_b = FederatedCache(
        store, shard=shard_index(job_key("default", "gb"), 2), shards=2,
        shard_key="gang",
    )
    cache_a.snapshot()
    cache_b.snapshot()
    won0 = metrics.federation_conflicts.value({"outcome": "won"})
    bind_gang(cache_a, "ga")
    bind_gang(cache_b, "gb")  # stale snapshot: conflicts, retries, wins
    assert metrics.federation_conflicts.value({"outcome": "won"}) == won0 + 1
    assert all(n == 1 for n in bind_counts.values())
    assert len(bind_counts) == 6
    assert fsck(store) == []


@pytest.mark.chaos
def test_chaos_stale_assign_fault_forces_conflict_retry():
    """federation.stale_assign zeroes the dispatched snapshot version:
    on a node with placement history the dispatch must lose once, meter
    a retry, and land on the refreshed version."""
    store = ClusterStore()
    seed_store(store, gangs=("ga",), members=2)
    store.create_pod(build_pod(name="warm", req=build_resource_list(cpu=1)))
    store.conditional_bind_many([("default", "warm", "n0")], store.version)
    cache = SchedulerCache(store, conditional_binds=True)
    cache.snapshot()
    retries0 = metrics.bind_retries.value()
    faults.registry.arm("federation.stale_assign", count=1)
    bind_gang(cache, "ga")
    assert metrics.bind_retries.value() == retries0 + 1
    assert all(
        store.get_pod("default", f"ga-p{m}").node_name == "n0" for m in range(2)
    )
    assert fsck(store) == []


@pytest.mark.chaos
def test_chaos_partition_skips_pump_and_heals(arbiter):
    """federation.partition drops the backend's transport: the pump
    skips the round (mirror stales, snapshot_age keeps growing), a
    conditional write surfaces BackendPartitioned; when the fault
    exhausts, the next pump delivers everything missed and writes land."""
    seed_store(arbiter.store, gangs=("g0",), members=1)
    backend = _backend_for(arbiter)
    backend.add_event_handler(PODS, EventHandler())
    assert backend.pump() == 0  # baseline healthy round
    v = backend.version
    t0 = backend._last_pump_ok
    arbiter.store.create_pod(build_pod(name="missed",
                                       req=build_resource_list(cpu=1)))
    # three drops: the pump round, the version probe, the write
    faults.registry.arm("federation.partition", count=3)
    assert backend.pump() == 0  # round skipped, no exception
    assert backend._last_pump_ok == t0  # staleness keeps accruing
    assert backend.get_pod("default", "missed") is None
    assert backend.snapshot_age() >= 0.0
    # version falls back to last-seen instead of failing snapshot()
    assert backend.version == backend._store_version
    with pytest.raises(BackendPartitioned):
        backend.conditional_bind_many([("default", "g0-p0", "n0")], v)
    # fault exhausted: the partition heals
    assert backend.pump() >= 1
    assert backend._last_pump_ok > t0
    assert backend.get_pod("default", "missed") is not None
    assert backend.conditional_bind_many(
        [("default", "g0-p0", "n0")], backend.version
    ) == 1
    backend.pump()
    assert backend.get_pod("default", "g0-p0").node_name == "n0"
    assert fsck(arbiter.store) == []


# -- mixed-version federation (wire protocol v2, ISSUE 17) -------------------


def test_v2_client_negotiates_down_against_v1_server():
    """A v2 client against a v1-only arbiter: the bare storeVersion
    reply IS the downgrade signal — no error path, no extra round trip,
    and every v1 surface (list, watch, conditional writes) keeps
    working byte-for-byte."""
    srv = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0, wire_protocol=1,
    )
    srv.start()
    try:
        seed_store(srv.store, gangs=("g0",), members=2)
        backend = LoopbackBackend(f"http://127.0.0.1:{srv.listen_port}")
        events: list[str] = []
        backend.add_event_handler(
            PODS, EventHandler(on_add=lambda o: events.append(o.name))
        )
        assert backend._protocol == 1 and backend._codec == "json"
        assert not backend.supports_txn()  # the cache's coalescing gate
        assert sorted(events) == ["g0-p0", "g0-p1"]
        v = backend.version
        assert backend.conditional_bind_many(
            [("default", "g0-p0", "n0")], v
        ) == 1
        assert backend.pump() >= 1  # per-kind v1 polls, full objects
        assert backend.get_pod("default", "g0-p0").node_name == "n0"
        assert fsck(srv.store) == []
    finally:
        srv.stop()


def test_v1_pinned_client_against_v2_server(arbiter):
    """The other direction of the matrix: an old (protocol-capped)
    client against a v2 arbiter runs the negotiated minimum — v1,
    json — and the server never pushes v2 surfaces at it."""
    seed_store(arbiter.store, gangs=("g0",), members=2)
    backend = LoopbackBackend(
        f"http://127.0.0.1:{arbiter.listen_port}", protocol=1
    )
    backend.add_event_handler(PODS, EventHandler())
    assert backend._protocol == 1 and backend._codec == "json"
    assert not backend.supports_txn()
    v = backend.version
    assert backend.conditional_bind_many([("default", "g0-p0", "n0")], v) == 1
    assert backend.pump() >= 1
    assert backend.get_pod("default", "g0-p0").node_name == "n0"
    assert fsck(arbiter.store) == []


def test_partition_forces_renegotiation_midrun(arbiter):
    """After any partition (injected or real) the peer we reconnect to
    may be a different server generation: the backend must drop its
    negotiated state and re-run version negotiation before the next
    request — and still deliver the events the dropped round missed."""
    seed_store(arbiter.store, gangs=("g0",), members=1)
    backend = _backend_for(arbiter)
    backend.add_event_handler(PODS, EventHandler())
    assert backend._protocol == 2 and backend.supports_txn()
    faults.registry.arm("federation.partition", count=1)
    arbiter.store.create_pod(
        build_pod(name="px", req=build_resource_list(cpu=1))
    )
    assert backend.pump() == 0  # dropped round
    assert backend._needs_negotiation
    assert not backend.supports_txn()  # coalescing gate closes until settled
    # next pass renegotiates first, then delivers the missed event
    assert backend.pump() >= 1
    assert backend._protocol == 2 and backend.supports_txn()
    assert backend.get_pod("default", "px") is not None


def test_rolling_downgrade_midrun_renegotiates_down(arbiter):
    """Rolling downgrade drill: the arbiter behind the same URL flips
    to v1 mid-run. The client's watchall 404s (_Unsupported), the SAME
    pump falls back to per-kind v1 polling — renegotiating on the way —
    and conditional writes keep landing; when the arbiter comes back as
    v2 the client upgrades again on its next negotiation."""
    seed_store(arbiter.store, gangs=("g0",), members=2)
    backend = _backend_for(arbiter)
    backend.add_event_handler(PODS, EventHandler())
    assert backend._protocol == 2 and backend.supports_txn()
    arbiter.wire_protocol = 1  # same listener, older build
    arbiter.store.create_pod(
        build_pod(name="px", req=build_resource_list(cpu=1))
    )
    assert backend.pump() >= 1  # watchall 404 -> v1 fallback, same round
    assert backend.get_pod("default", "px") is not None
    assert backend._protocol == 1 and backend._codec == "json"
    assert not backend.supports_txn()
    v = backend.version
    assert backend.conditional_bind_many([("default", "g0-p0", "n0")], v) == 1
    # heal: the arbiter rolls forward again
    arbiter.wire_protocol = 2
    backend._mark_renegotiate()
    assert backend.version == arbiter.store.version
    assert backend._protocol == 2 and backend.supports_txn()
    assert fsck(arbiter.store) == []
