"""Named counterparts of the reference's e2e suite cases that had no
dedicated scenario test yet (reference test/e2e/job.go, predicates.go;
the rest of that suite — gang/full-occupied, single preemption,
best-effort, statement, job priority, reclaim, node/pod affinity,
taints, least-requested — is covered across test_actions.py,
test_xla_*.py and test_interpod_affinity.py).

Where the reference case leans on cluster controllers (replicaset
recreation, kubelet restarts), these tests keep the *scheduler-visible*
contract: the same pods in, the same binds/evictions out."""

from __future__ import annotations

import time

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.server import SchedulerServer
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

FULL_PIPELINE_CONF = """
actions: "enqueue, reclaim, allocate, backfill, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_multiple_preemption(tmp_path):
    """reference job.go:182-221 ("Multiple Preemption"): a low-priority
    job holds the whole cluster; TWO higher-priority gangs arrive and
    both carve out their min members through preempt — end to end
    through the live server loop (evictions delete pods from the store,
    freeing resources that the next cycles re-bind)."""
    conf = tmp_path / "conf.yaml"
    conf.write_text(FULL_PIPELINE_CONF)
    srv = SchedulerServer(
        listen_address="127.0.0.1:0", schedule_period=0.05, scheduler_conf=str(conf)
    )
    srv.start()
    store = srv.store
    try:
        for i in range(4):
            store.create_node(
                build_node(f"n{i}", build_resource_list(cpu=2, memory="4Gi", pods=10))
            )
        # low-priority job occupying every slot (8 x 1cpu)
        store.create_pod_group(build_pod_group("low", min_member=1))
        for i in range(8):
            store.create_pod(
                build_pod(
                    name=f"low-{i}",
                    group_name="low",
                    node_name=f"n{i // 2}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=1,
                )
            )
        # two high-priority gangs, each needing 2 slots
        for g in ("high-a", "high-b"):
            store.create_pod_group(build_pod_group(g, min_member=2))
            for i in range(2):
                store.create_pod(
                    build_pod(
                        name=f"{g}-{i}",
                        group_name=g,
                        req=build_resource_list(cpu=1, memory="1Gi"),
                        priority=9,
                    )
                )

        def both_gangs_bound():
            pods = {p.metadata.name: p for p in store.list("pods")}
            return all(
                pods.get(f"{g}-{i}") is not None and pods[f"{g}-{i}"].node_name
                for g in ("high-a", "high-b")
                for i in range(2)
            )

        wait_until(both_gangs_bound, what="both high-priority gangs bound")
        # preemption really happened: some low pods were evicted (deleted)
        low_left = [p for p in store.list("pods") if p.metadata.name.startswith("low-")]
        assert len(low_left) < 8, "no victim was preempted"
    finally:
        srv.stop()


def test_task_priority_within_one_job():
    """reference job.go:291-330 ("TaskPriority"): one job whose tasks
    carry different priorities on a cluster with room for only half —
    the master-priority task and the highest-priority workers win the
    slots (TaskOrderFn by priority, session_plugins.go:308-341)."""
    nodes = [build_node("n0", build_resource_list(cpu=4, memory="8Gi", pods=10))]
    pods = []
    # 8 workers (pri 1) + 1 master (pri 9); capacity = 4 slots
    for i in range(8):
        pods.append(
            build_pod(
                name=f"worker-{i}",
                group_name="job",
                req=build_resource_list(cpu=1, memory="512Mi"),
                priority=1,
            )
        )
    pods.append(
        build_pod(
            name="master",
            group_name="job",
            req=build_resource_list(cpu=1, memory="512Mi"),
            priority=9,
        )
    )
    cluster = build_cluster(
        pods, nodes, [build_pod_group("job", min_member=4)], [build_queue("default")]
    )
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(FULL_PIPELINE_CONF).tiers)
    get_action("allocate").execute(ssn)
    close_session(ssn)
    binds = dict(cache.binder.binds)
    assert len(binds) == 4
    assert "default/master" in binds, "master-priority task must win a slot"
    assert sum(1 for k in binds if k.startswith("default/worker-")) == 3


def test_hostport_conflicts_spread_across_nodes():
    """reference predicates.go:78-105 ("Hostport"): 2*nn pods sharing one
    hostPort on nn nodes — exactly nn bind (one per node), nn stay
    pending on the port conflict."""
    nn = 3
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
        for i in range(nn)
    ]
    pods = []
    for i in range(nn * 2):
        pod = build_pod(
            name=f"hp-{i}", group_name="hp-job",
            req=build_resource_list(cpu=1, memory="512Mi"),
        )
        pod.containers[0].ports = [28080]
        pods.append(pod)
    cluster = build_cluster(
        pods, nodes, [build_pod_group("hp-job", min_member=nn)], [build_queue("default")]
    )
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(FULL_PIPELINE_CONF).tiers)
    get_action("allocate").execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    bound_nodes = [v[1] for v in state.values() if v[1]]
    assert len(bound_nodes) == nn, f"expected one bind per node, got {state}"
    assert len(set(bound_nodes)) == nn, "hostport conflict must spread binds"
    assert sum(1 for v in state.values() if v[0] == TaskStatus.PENDING) == nn


def test_xla_parity_on_these_scenarios():
    """The xla pipeline reproduces the TaskPriority and Hostport
    outcomes exactly (the Multiple Preemption loop is covered by the
    pipeline parity sweep in test_pipeline_parity.py)."""

    def run(action_name, mk):
        cache = FakeCache(mk())
        ssn = open_session(cache, parse_scheduler_conf(FULL_PIPELINE_CONF).tiers)
        get_action(action_name).execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds)

    def task_priority_cluster():
        nodes = [build_node("n0", build_resource_list(cpu=4, memory="8Gi", pods=10))]
        pods = [
            build_pod(
                name=f"worker-{i}", group_name="job",
                req=build_resource_list(cpu=1, memory="512Mi"), priority=1,
            )
            for i in range(8)
        ]
        pods.append(
            build_pod(
                name="master", group_name="job",
                req=build_resource_list(cpu=1, memory="512Mi"), priority=9,
            )
        )
        return build_cluster(
            pods, nodes, [build_pod_group("job", min_member=4)], [build_queue("default")]
        )

    def hostport_cluster():
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
            for i in range(3)
        ]
        pods = []
        for i in range(6):
            pod = build_pod(
                name=f"hp-{i}", group_name="hp-job",
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
            pod.containers[0].ports = [28080]
            pods.append(pod)
        return build_cluster(
            pods, nodes, [build_pod_group("hp-job", min_member=3)], [build_queue("default")]
        )

    for mk in (task_priority_cluster, hostport_cluster):
        assert run("xla_allocate", mk) == run("allocate", mk), mk.__name__
