"""Streaming mode (ISSUE 8 tentpole): event-driven micro-cycles on the
resident node table, drained between periodic full cycles.

The headline invariants driven end to end here:

- **parity**: Poisson gang arrivals served by micro-cycles produce
  bind-for-bind the same placements as the same arrivals served by
  full cycles alone (conf without drf/proportion — the fairness
  plugins micro tiers exclude by design);
- **degrade, never drop**: an injected ``stream.micro_cycle`` fault or
  external bound-pod churn invalidates the resident table and falls
  back to a full cycle, with every arrival still binding (mutation
  detector armed suite-wide by conftest);
- **crash consistency**: a leader killed mid-micro-dispatch leaves the
  PR-3 write-intent journal holding the in-flight suffix, and a
  standby's reconciliation + full cycle converge to the uninterrupted
  twin's placements with zero lost and zero duplicate binds.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

import pytest

from kube_batch_tpu import faults, metrics
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.cache import StoreBinder
from kube_batch_tpu.cache.store import NODES, POD_GROUPS, PODS, QUEUES, EventHandler
from kube_batch_tpu.conf import Tier, PluginOption, parse_scheduler_conf
from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.streaming import (
    MICRO_EXCLUDED_PLUGINS,
    StreamState,
    StreamTrigger,
    gang_key_of,
    micro_tiers,
)
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# Serial pipeline without drf/proportion: micro tiers drop those two,
# so exact streaming-vs-full parity is stated over this conf.
STREAM_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: {streaming}
"""


def seed_cluster(store: ClusterStore, nodes: int = 6) -> None:
    store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=64))
        )


def arrive_gang(store: ClusterStore, name: str, members: int) -> None:
    store.create_pod_group(build_pod_group(name, min_member=members))
    for m in range(members):
        store.create_pod(
            build_pod(
                name=f"{name}-p{m}", group_name=name,
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
        )


def make_streaming_scheduler(store, tmp_path, streaming=True, period=5.0,
                             journal=None, binder=None):
    conf = tmp_path / f"conf-{streaming}.yaml"
    conf.write_text(STREAM_CONF.format(streaming=str(streaming).lower()))
    cache = SchedulerCache(store, journal=journal, binder=binder)
    return cache, Scheduler(cache, scheduler_conf=str(conf), schedule_period=period)


def placements(store) -> dict:
    return {f"{p.namespace}/{p.name}": p.node_name for p in store.list(PODS)}


def all_bound(store) -> bool:
    pods = store.list(PODS)
    return bool(pods) and all(p.node_name for p in pods)


# -- units -------------------------------------------------------------------


def test_micro_tiers_drop_fairness_plugins_and_empty_tiers():
    tiers = [
        Tier(plugins=[PluginOption(name="priority"), PluginOption(name="gang")]),
        Tier(plugins=[PluginOption(name="drf"), PluginOption(name="proportion")]),
        Tier(plugins=[PluginOption(name="predicates"), PluginOption(name="drf")]),
    ]
    out = micro_tiers(tiers)
    assert [[p.name for p in t.plugins] for t in out] == [
        ["priority", "gang"], ["predicates"],
    ]
    assert MICRO_EXCLUDED_PLUGINS == {"drf", "proportion"}
    # the original conf tiers are untouched (they are reused every cycle)
    assert [p.name for p in tiers[1].plugins] == ["drf", "proportion"]


def test_gang_key_of_annotated_and_shadow_pods():
    annotated = build_pod(name="a", group_name="g7")
    assert gang_key_of(annotated) == "default/g7"
    bare = build_pod(name="b")
    # shadow-job key: matches cache.py _resolve_shadow_job
    assert gang_key_of(bare) == (
        f"default/{bare.metadata.owner_job or bare.metadata.uid}"
    )


def test_conf_streaming_key_parses():
    assert parse_scheduler_conf("streaming: true").streaming is True
    assert parse_scheduler_conf("actions: allocate").streaming is False


def test_trigger_event_rules():
    trig = StreamTrigger()
    pending = build_pod(name="p0", group_name="g0")
    uid = pending.metadata.uid

    # pending-pod add: gang dirty, arrival stamped, wake
    trig._on_event(PODS, uid, pending, None)
    assert trig.wait(0) and trig.backlog_pods() == 1
    work = trig.drain()
    assert work.gangs == {"default/g0"} and not work.stale

    # pending->pending condition echo: no wake (self-trigger guard)
    trig._on_event(PODS, uid, pending, pending)
    assert not trig.wait(0)

    # bind echo: arrival closed, still no wake, gang kept until pruned
    bound = dataclasses.replace(pending, node_name="n1")
    trig._on_event(PODS, uid, bound, pending)
    assert trig.backlog_pods() == 0 and not trig.wait(0)
    assert trig.drain().gangs == {"default/g0"}
    trig.prune({"default/g0"})
    assert trig.drain().gangs == set()

    # unbind echo: the pod is a fresh arrival again
    trig._on_event(PODS, uid, pending, bound)
    assert trig.wait(0) and trig.backlog_pods() == 1
    assert trig.drain().gangs == {"default/g0"}

    # node churn: recorded as a patch (latest wins, None = delete)
    node = build_node("nx", build_resource_list(cpu=4))
    trig._on_event(NODES, "nx", node, None)
    trig._on_event(NODES, "ny", None, build_node("ny", build_resource_list(cpu=4)))
    assert trig.wait(0)
    work = trig.drain()
    assert work.node_patches == {"nx": node, "ny": None}

    # podgroup add dirties the gang; queue churn just wakes
    trig._on_event(POD_GROUPS, "default/g9", build_pod_group("g9"), None)
    trig._on_event(QUEUES, "default", build_queue("default"), None)
    assert trig.wait(0)
    assert trig.drain().gangs == {"default/g0", "default/g9"}
    trig.prune({"default/g0", "default/g9"})

    # status-only podgroup write-back (what close_session emits for
    # every session job): must NOT re-dirty the gang
    pg = build_pod_group("g9")
    pg2 = dataclasses.replace(pg)
    trig._on_event(POD_GROUPS, "default/g9", pg2, pg)
    assert not trig.wait(0) and trig.drain().gangs == set()
    # a spec change (min_member edit) does dirty it
    pg3 = dataclasses.replace(
        pg, spec=dataclasses.replace(pg.spec, min_member=5)
    )
    trig._on_event(POD_GROUPS, "default/g9", pg3, pg)
    assert trig.wait(0) and trig.drain().gangs == {"default/g9"}

    # bound-pod churn from outside any session: resident table is stale
    trig._on_event(PODS, uid, None, bound)
    work = trig.drain()
    assert work.stale and "deleted outside a cycle" in work.stale_reason


def test_stream_state_adopt_patch_invalidate():
    st = StreamState()
    assert not st.valid

    class FakeSession:
        nodes = {"n0": None}

    st.adopt_full_cycle(FakeSession())
    assert st.valid and "n0" in st.nodes
    st.apply_node_patches({"n1": build_node("n1", build_resource_list(cpu=2))})
    assert set(st.nodes) == {"n0", "n1"}
    st.apply_node_patches({"n0": None})
    assert set(st.nodes) == {"n1"}
    st.adopt_full_cycle(FakeSession(), aborted=True)
    assert not st.valid and st.nodes is None


# -- end to end --------------------------------------------------------------


def test_stopped_streaming_loop_leaves_zero_listeners(tmp_path):
    """A streaming loop that has been stopped (cleanly or by exception)
    must return the store-listener registry to its pre-start count — a
    leaked listener keeps firing into the dead loop on every store
    event (KBT-C005's hazard class)."""
    from kube_batch_tpu.ops import encode_cache

    before = encode_cache.listener_count()
    store = ClusterStore()
    seed_cluster(store)
    _, sched = make_streaming_scheduler(store, tmp_path, streaming=True, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        arrive_gang(store, "g0", members=4)
        wait_until(lambda: all_bound(store), what="gang g0 bound")
        assert encode_cache.listener_count() == before + 1
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert encode_cache.listener_count() == before


def test_streaming_binds_arrivals_between_full_cycles(tmp_path):
    """With the full-cycle period far longer than the test, everything
    after the initial cycle must bind through micro-cycles."""
    store = ClusterStore()
    seed_cluster(store)
    _, sched = make_streaming_scheduler(store, tmp_path, streaming=True, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        for g in range(3):
            arrive_gang(store, f"g{g}", members=4)
            wait_until(lambda g=g: all(
                p.node_name for p in store.list(PODS)
                if p.name.startswith(f"g{g}-")
            ), what=f"gang g{g} bound via micro-cycle")
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert sched.micro_cycles_run > 0
    assert all_bound(store)


def test_streaming_vs_full_cycle_poisson_parity(tmp_path):
    """THE parity invariant: Poisson arrivals drained by micro-cycles +
    backstop full cycles place bind-for-bind identically to full cycles
    alone over the same arrival sequence."""
    rng = random.Random(42)
    gangs = [(f"g{i}", rng.choice([2, 3, 4])) for i in range(8)]
    delays = [rng.expovariate(1 / 0.004) for _ in gangs]

    def run(streaming: bool) -> tuple[dict, Scheduler]:
        store = ClusterStore()
        seed_cluster(store)
        _, sched = make_streaming_scheduler(
            store, tmp_path, streaming=streaming,
            period=0.25 if streaming else 0.02,
        )
        stop = threading.Event()
        t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
        t.start()
        try:
            for (name, members), delay in zip(gangs, delays):
                time.sleep(delay if streaming else 0)
                arrive_gang(store, name, members)
            wait_until(lambda: all_bound(store), what="all gangs bound")
        finally:
            stop.set()
            t.join(timeout=10.0)
        return placements(store), sched

    stream_placed, stream_sched = run(True)
    full_placed, _ = run(False)
    assert stream_placed == full_placed, "streaming must be bind-for-bind full-cycle"
    assert stream_sched.micro_cycles_run > 0, "streaming run never took the micro path"


def test_micro_cycle_fault_degrades_to_full_cycle_no_pod_dropped(tmp_path):
    """Chaos: the ``stream.micro_cycle`` point fires on the first micro
    attempt; the loop degrades to an immediate full cycle and every
    arrival still binds (detector armed suite-wide by conftest)."""
    faults.registry.arm("stream.micro_cycle", count=1)
    store = ClusterStore()
    seed_cluster(store)
    _, sched = make_streaming_scheduler(store, tmp_path, streaming=True, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        # wait out the initial full cycle: if g0 arrives before it, the
        # full cycle binds g0 and the armed fault survives to ambush a
        # later micro instead of the one this test scripts
        wait_until(
            lambda: sched._stream_state is not None and sched._stream_state.valid,
            what="resident table adopted",
        )
        arrive_gang(store, "g0", members=4)
        wait_until(lambda: all_bound(store), what="gang bound despite micro fault")
        # the resident table was rebuilt by the degrade full cycle;
        # later arrivals flow through micro-cycles again
        before = sched.micro_cycles_run
        arrive_gang(store, "g1", members=4)
        wait_until(lambda: all_bound(store), what="post-fault gang bound")
        wait_until(
            lambda: sched.micro_cycles_run > before,
            what="micro path resumed after the degrade",
        )
    finally:
        stop.set()
        t.join(timeout=10.0)
    _, _, fired = faults.registry.active()["stream.micro_cycle"]
    assert fired == 1


def test_external_bound_churn_invalidates_resident(tmp_path):
    """A pod bound by someone else (another scheduler, a replayed
    object) appears in the store: the resident table cannot absorb it,
    so streaming degrades to a full cycle and keeps serving."""
    store = ClusterStore()
    seed_cluster(store)
    _, sched = make_streaming_scheduler(store, tmp_path, streaming=True, period=30.0)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        arrive_gang(store, "g0", members=3)
        wait_until(lambda: all_bound(store), what="first gang bound")
        # external actor binds a pod wholesale (add, not our update echo)
        store.create_pod(build_pod(name="alien", node_name="n0"))
        arrive_gang(store, "g1", members=3)
        wait_until(lambda: all_bound(store), what="gang bound after external churn")
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert placements(store)["default/alien"] == "n0"


# -- crash consistency (PR 3 journal) ----------------------------------------


class _LeaderKilled(BaseException):
    """SIGKILL stand-in: BaseException so no retry/resync ladder can
    'survive' it — the dispatch dies exactly where a killed process
    would (same device as test_recovery's chaos e2e)."""


class DyingBinder(StoreBinder):
    def __init__(self, store, die_after: int) -> None:
        super().__init__(store)
        self.left = die_after

    def bind(self, pod, hostname: str) -> None:
        if self.left <= 0:
            raise _LeaderKilled()
        self.left -= 1
        super().bind(pod, hostname)


def _count_bind_events(store) -> dict:
    counts: dict[str, int] = {}

    def on_update(old, new):
        if not old.node_name and new.node_name:
            counts[f"{new.namespace}/{new.name}"] = (
                counts.get(f"{new.namespace}/{new.name}", 0) + 1
            )

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    return counts


def test_chaos_leader_killed_mid_micro_bind_standby_reconciles(tmp_path):
    """A leader running streaming mode dies mid-micro-cycle dispatch
    (after journal appends, after some store writes landed). The
    standby's journal reconciliation plus one full cycle converge to
    the uninterrupted twin's placements: zero lost, zero duplicate."""
    total = 12  # 2 gangs x 6

    # uninterrupted twin: full cycle over the complete arrival set
    twin = ClusterStore()
    seed_cluster(twin, nodes=4)
    for g in range(2):
        arrive_gang(twin, f"g{g}", members=6)
    _, sched_t = make_streaming_scheduler(twin, tmp_path, streaming=False)
    sched_t.run_once()
    expected = placements(twin)
    assert all(expected.values()) and len(expected) == total

    # the real run: synchronous streaming loop (no cache.run() -> writes
    # are inline, so the binder's death IS the scheduler thread's death)
    store = ClusterStore()
    seed_cluster(store, nodes=4)
    bind_counts = _count_bind_events(store)
    journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    _, sched = make_streaming_scheduler(
        store, tmp_path, streaming=True,
        journal=journal, binder=DyingBinder(store, die_after=4),
    )
    from kube_batch_tpu.streaming import StreamState, StreamTrigger

    trigger = StreamTrigger()
    state = StreamState()
    sched._stream_trigger, sched._stream_state = trigger, state
    trigger.attach()
    try:
        sched.run_once()  # empty world; adopts the resident node table
        assert state.valid
        for g in range(2):
            arrive_gang(store, f"g{g}", members=6)
        with pytest.raises(_LeaderKilled):
            sched.run_micro(trigger.drain())
    finally:
        trigger.detach()
    assert not state.valid, "a dead micro-cycle must invalidate the resident table"
    landed = {k: v for k, v in placements(store).items() if v}
    assert 0 < len(landed) < total, "kill must land mid-dispatch"
    orphans = WriteIntentJournal.replay(journal.path).orphans
    assert orphans, "journal must hold the in-flight suffix"

    # standby: reconcile the journal, then one ordinary full cycle
    standby_journal = WriteIntentJournal(str(tmp_path / "leader.wal"))
    report = reconcile_journal(standby_journal, store)
    assert report.redispatched == len(orphans)
    assert report.rolled_back == 0
    _, sched_b = make_streaming_scheduler(store, tmp_path, streaming=False)
    sched_b.run_once()

    assert placements(store) == expected, "standby must converge to the twin"
    assert all(n == 1 for n in bind_counts.values()), f"duplicate binds: {bind_counts}"
    assert set(bind_counts) == set(expected), "lost binds"
    standby_journal.close()


# -- metrics -----------------------------------------------------------------


def test_streaming_metrics_families_render():
    metrics.observe_time_to_bind(0.004)
    metrics.register_micro_cycle("ok")
    metrics.set_streaming_backlog(3)
    text = metrics.render_prometheus_text()
    assert "kube_batch_tpu_time_to_bind_seconds" in text
    assert "kube_batch_tpu_micro_cycles_total" in text
    assert "kube_batch_tpu_streaming_backlog_pods" in text
