"""L6/L7 integration: the scheduler loop against a live, mutating
cluster, the HTTP surface, conf hot-reload, and leader election
(VERDICT r2 item 4)."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from kube_batch_tpu import metrics
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.server import LeaderElector, SchedulerServer
from kube_batch_tpu.testing import (
    build_node,
    build_pod,
    build_pod_group,
    build_resource_list,
)


def wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def server():
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=0.05)
    srv.start()
    yield srv
    srv.stop()


def http_get(server, path: str) -> tuple[int, str]:
    url = f"http://127.0.0.1:{server.listen_port}{path}"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_loop_schedules_live_mutating_cluster(server):
    """Pods created while the loop runs get bound over subsequent cycles
    — the scheduler behaves as a continuously running service, not a
    one-shot library call."""
    store = server.store
    for i in range(2):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=10))
        )
    # Gang of 2 via the full default pipeline (enqueue flips the
    # PodGroup Pending -> Inqueue, allocate binds).
    store.create_pod_group(build_pod_group("job-a", min_member=2))
    for i in range(2):
        store.create_pod(
            build_pod(name=f"a{i}", group_name="job-a",
                      req=build_resource_list(cpu=1, memory="1Gi"))
        )
    wait_until(
        lambda: all(p.node_name for p in store.list("pods")),
        what="first gang bound",
    )

    # Mutate the live cluster: a second job arrives mid-flight.
    store.create_pod_group(build_pod_group("job-b", min_member=3))
    for i in range(3):
        store.create_pod(
            build_pod(name=f"b{i}", group_name="job-b",
                      req=build_resource_list(cpu=1, memory="1Gi"))
        )
    wait_until(
        lambda: all(p.node_name for p in store.list("pods")),
        what="second gang bound in a later cycle",
    )
    assert len([p for p in store.list("pods") if p.node_name]) == 5


def test_gang_larger_than_cluster_stays_pending(server):
    store = server.store
    store.create_node(build_node("n0", build_resource_list(cpu=2, pods=10)))
    store.create_pod_group(build_pod_group("big", min_member=3))
    for i in range(3):
        store.create_pod(
            build_pod(name=f"g{i}", group_name="big", req=build_resource_list(cpu=2))
        )
    time.sleep(0.3)  # several cycles
    # Gang barrier: nothing partially bound.
    assert all(not p.node_name for p in store.list("pods"))


def test_metrics_endpoint_scrapes_live_latencies(server):
    wait_until(
        lambda: metrics.schedule_attempts.value() > 0, what="first cycle"
    )
    status, body = http_get(server, "/metrics")
    assert status == 200
    assert "kube_batch_tpu_e2e_scheduling_latency_count" in body
    assert "kube_batch_tpu_action_scheduling_latency" in body
    # A real nonzero e2e observation landed.
    for line in body.splitlines():
        if line.startswith("kube_batch_tpu_e2e_scheduling_latency_count"):
            assert float(line.split()[-1]) > 0
            break
    else:
        raise AssertionError("e2e latency family missing")
    assert 'action="allocate"' in body


def test_healthz_and_version(server):
    assert http_get(server, "/healthz") == (200, "ok")
    status, body = http_get(server, "/version")
    assert status == 200
    assert "API Version: v1alpha1" in body


def test_queue_api_crud(server):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.listen_port}/apis/v1alpha1/queues",
        data=json.dumps({"name": "research", "weight": 4}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 201
    status, body = http_get(server, "/apis/v1alpha1/queues")
    items = {q["name"]: q["weight"] for q in json.loads(body)["items"]}
    assert items["research"] == 4
    assert "default" in items  # bootstrapped default queue
    # The cache mirrors it for the next session.
    wait_until(lambda: "research" in server.cache.queues, what="queue in cache")

    req = urllib.request.Request(
        f"http://127.0.0.1:{server.listen_port}/apis/v1alpha1/queues/research",
        method="DELETE",
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 200
    status, body = http_get(server, "/apis/v1alpha1/queues")
    assert "research" not in body


def test_loop_with_xla_allocate_pipeline(tmp_path):
    """The XLA solve runs as the conf-selected allocator inside the live
    loop: enqueue gates, xla_allocate encodes + solves + replays."""
    conf = tmp_path / "scheduler.conf"
    conf.write_text(
        'actions: "enqueue, xla_allocate"\n'
        "tiers:\n"
        "- plugins:\n  - name: priority\n  - name: gang\n  - name: conformance\n"
        "- plugins:\n  - name: drf\n  - name: predicates\n"
        "  - name: proportion\n  - name: nodeorder\n"
    )
    srv = SchedulerServer(
        listen_address="127.0.0.1:0",
        schedule_period=0.05,
        scheduler_conf=str(conf),
    )
    srv.start()
    try:
        for i in range(2):
            srv.store.create_node(
                build_node(f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=10))
            )
        srv.store.create_pod_group(build_pod_group("xj", min_member=3))
        for i in range(3):
            srv.store.create_pod(
                build_pod(name=f"x{i}", group_name="xj",
                          req=build_resource_list(cpu=1, memory="1Gi"))
            )
        wait_until(
            lambda: all(p.node_name for p in srv.store.list("pods")),
            timeout=60,  # first cycle pays jit compile
            what="xla pipeline bound the gang",
        )
    finally:
        srv.stop()


def test_conf_hot_reload(tmp_path):
    """A conf push takes effect on the next cycle without a restart."""
    conf = tmp_path / "scheduler.conf"
    conf.write_text(
        'actions: "enqueue, allocate"\n'
        "tiers:\n- plugins:\n  - name: gang\n  - name: priority\n"
    )
    store = ClusterStore()
    cache = SchedulerCache(store)
    sched = Scheduler(cache, scheduler_conf=str(conf), schedule_period=0.05)
    assert [a.name for a in sched.actions] == ["enqueue", "allocate"]
    conf.write_text(
        'actions: "enqueue, allocate, backfill"\n'
        "tiers:\n- plugins:\n  - name: gang\n  - name: priority\n"
    )
    sched.run_once()
    assert [a.name for a in sched.actions] == ["enqueue", "allocate", "backfill"]
    # A broken conf keeps the previous good pipeline.
    conf.write_text('actions: "no-such-action"\n')
    sched.run_once()
    assert [a.name for a in sched.actions] == ["enqueue", "allocate", "backfill"]
    cache.stop()


def test_leader_election_mutual_exclusion(tmp_path):
    lock = str(tmp_path / "leader.lock")
    a = LeaderElector(lock, "a")
    b = LeaderElector(lock, "b")
    assert a.acquire(blocking=False)
    assert not b.acquire(blocking=False)  # standby cannot grab the lease
    a.release()
    assert b.acquire(blocking=False)  # failover after the leader lets go
    b.release()


class TestCLI:
    """kbt-ctl against a live server (pkg/cli/queue parity)."""

    def test_queue_create_list_delete(self):
        import io

        from kube_batch_tpu.cli import main
        from kube_batch_tpu.server import SchedulerServer

        server = SchedulerServer(listen_address="127.0.0.1:0")
        server.start()
        try:
            addr = f"http://127.0.0.1:{server.listen_port}"
            assert main(["--server", addr, "queue", "create", "--name", "gold", "--weight", "5"]) == 0
            out = io.StringIO()
            assert main(["--server", addr, "queue", "list"], out=out) == 0
            lines = out.getvalue().splitlines()
            assert lines[0].startswith("Name")
            assert any(l.startswith("gold") and "5" in l for l in lines[1:])
            assert main(["--server", addr, "queue", "delete", "--name", "gold"]) == 0
            out = io.StringIO()
            assert main(["--server", addr, "queue", "list"], out=out) == 0
            assert not any(l.startswith("gold") for l in out.getvalue().splitlines())
        finally:
            server.stop()

    def test_version_command(self):
        import io

        from kube_batch_tpu.cli import main

        out = io.StringIO()
        assert main(["version"], out=out) == 0
        assert "API Version" in out.getvalue()

    def test_create_duplicate_errors(self):
        from kube_batch_tpu.cli import main
        from kube_batch_tpu.server import SchedulerServer

        server = SchedulerServer(listen_address="127.0.0.1:0")
        server.start()
        try:
            addr = f"http://127.0.0.1:{server.listen_port}"
            assert main(["--server", addr, "queue", "create", "--name", "dup"]) == 0
            assert main(["--server", addr, "queue", "create", "--name", "dup"]) == 1
        finally:
            server.stop()


def test_workload_ingestion_over_http(server):
    """An external control plane feeds nodes, a PodGroup, and pods purely
    over the HTTP API; the loop schedules them and the pod list reflects
    the binds — the full API-server-substitute round trip."""
    import urllib.request

    addr = f"http://127.0.0.1:{server.listen_port}"

    def post(path, payload):
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 201, resp.status

    for i in range(2):
        post("/apis/v1alpha1/nodes", {"name": f"hn{i}", "allocatable": {"cpu": 4, "memory": "8Gi", "pods": 10}})
    post("/apis/v1alpha1/podgroups", {"name": "web", "min_member": 2})
    for i in range(2):
        post(
            "/apis/v1alpha1/pods",
            {"name": f"web-{i}", "group": "web", "requests": {"cpu": 1, "memory": "1Gi"}},
        )

    def bound():
        _, body = http_get(server, "/apis/v1alpha1/pods")
        items = json.loads(body)["items"]
        return sum(1 for p in items if p["node"]) == 2

    wait_until(bound, what="pods bound via HTTP-fed cluster")

    # delete one pod over HTTP; the store/cache must take it
    req = urllib.request.Request(f"{addr}/apis/v1alpha1/pods/default/web-0", method="DELETE")
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 200
    _, body = http_get(server, "/apis/v1alpha1/pods")
    assert len(json.loads(body)["items"]) == 1


def test_example_confs_load_and_schedule(tmp_path):
    """Both shipped example confs parse, resolve every named action and
    plugin, and schedule a pod through the loop."""
    import pathlib

    expected_actions = {
        "scheduler-conf.yaml": ["enqueue", "reclaim", "allocate", "backfill", "preempt"],
        "scheduler-conf-tpu.yaml": [
            "enqueue", "xla_reclaim", "xla_allocate", "xla_backfill", "xla_preempt",
        ],
    }
    for conf in ("scheduler-conf.yaml", "scheduler-conf-tpu.yaml"):
        path = pathlib.Path(__file__).resolve().parent.parent / "examples" / conf
        assert path.is_file(), f"missing example conf {path}"
        srv = SchedulerServer(
            listen_address="127.0.0.1:0",
            schedule_period=0.05,
            scheduler_conf=str(path),
        )
        srv.start()
        try:
            srv.store.create_node(
                build_node("n0", build_resource_list(cpu=4, memory="8Gi", pods=10))
            )
            srv.store.create_pod_group(build_pod_group("pg", min_member=1))
            srv.store.create_pod(
                build_pod(name="p0", group_name="pg", req=build_resource_list(cpu=1, memory="1Gi"))
            )
            wait_until(
                lambda: (srv.store.get_pod("default", "p0") or build_pod()).node_name
                == "n0",
                timeout=20,
                what=f"bind under {conf}",
            )
            # the conf really loaded (an unreadable path would silently
            # fall back to the default pipeline and pass vacuously)
            assert [a.name for a in srv.scheduler.actions] == expected_actions[conf]
        finally:
            srv.stop()


def test_ingestion_rejects_type_poisoned_pods(server):
    """Wrongly-typed fields must be rejected at the door with a 400 —
    a str priority stored would TypeError inside every scheduling cycle."""
    import urllib.error
    import urllib.request

    addr = f"http://127.0.0.1:{server.listen_port}"

    def post(path, payload):
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    assert post("/apis/v1alpha1/pods", {"name": "p", "priority": "high"}) == 400
    assert post("/apis/v1alpha1/pods", {"name": "p", "priority": True}) == 400  # bool
    assert post("/apis/v1alpha1/pods", {"name": "p", "labels": "x"}) == 400
    assert post("/apis/v1alpha1/pods", {"name": "p", "requests": "2cpu"}) == 400
    assert post("/apis/v1alpha1/pods", {"priority": 5}) == 400  # no name
    assert post("/apis/v1alpha1/nodes", {"name": "n", "allocatable": "big"}) == 400
    # int-as-string priority is coerced, not rejected
    assert post("/apis/v1alpha1/pods", {"name": "ok", "priority": "5"}) == 201
    assert post("/apis/v1alpha1/pods", {"name": "ok", "priority": 5}) == 409  # dup


def test_pdb_and_priorityclass_ingestion(server):
    """PDBs (legacy shadow-gang source) and PriorityClasses round-trip
    over HTTP and actually steer scheduling: the priority class resolves
    the pod's priority through the cache."""
    import urllib.request

    addr = f"http://127.0.0.1:{server.listen_port}"

    def post(path, payload):
        req = urllib.request.Request(
            f"{addr}{path}", data=json.dumps(payload).encode(), method="POST"
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status

    assert post("/apis/v1alpha1/priorityclasses", {"name": "gold", "value": 9}) == 201
    assert (
        post(
            "/apis/v1alpha1/poddisruptionbudgets",
            {"name": "web-pdb", "min_available": 2, "selector": {"app": "web"}},
        )
        == 201
    )
    _, body = http_get(server, "/apis/v1alpha1/priorityclasses")
    assert json.loads(body)["items"] == [
        {"name": "gold", "value": 9, "global_default": False}
    ]
    _, body = http_get(server, "/apis/v1alpha1/poddisruptionbudgets")
    assert json.loads(body)["items"][0]["min_available"] == 2

    # PDB delete route (shadow-gang constraints must be removable)
    req = urllib.request.Request(
        f"{addr}/apis/v1alpha1/poddisruptionbudgets/default/web-pdb", method="DELETE"
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 200
    _, body = http_get(server, "/apis/v1alpha1/poddisruptionbudgets")
    assert json.loads(body)["items"] == []

    # a pod using the class gets its priority resolved in the snapshot
    post("/apis/v1alpha1/nodes", {"name": "pn0", "allocatable": {"cpu": 2, "memory": "4Gi", "pods": 10}})
    post(
        "/apis/v1alpha1/pods",
        {
            "name": "gold-pod",
            "requests": {"cpu": 1, "memory": "1Gi"},
            "priority_class_name": "gold",
        },
    )
    wait_until(
        lambda: (server.store.get_pod("default", "gold-pod") or build_pod()).node_name
        == "pn0",
        what="gold pod bound",
    )
    snap = server.cache.snapshot()
    task = next(
        t for j in snap.jobs.values() for t in j.tasks.values() if t.name == "gold-pod"
    )
    assert task.priority == 9


# -- watch API (VERDICT r3 item 4) ------------------------------------------


def http_get_json(server, path: str) -> dict:
    _, body = http_get(server, path)
    return json.loads(body)


def test_watch_observes_bind_event_without_polling(server):
    """An external client lists pods (taking the resourceVersion), then
    long-polls the watch endpoint: the bind arrives as MODIFIED events —
    no re-GET of the pod list anywhere."""
    listing = http_get_json(server, "/apis/v1alpha1/pods")
    since = listing["resourceVersion"]

    store = server.store
    store.create_node(build_node("n1", build_resource_list(cpu=4, memory="8Gi", pods=10)))
    store.create_pod_group(build_pod_group("pg-w", min_member=1))
    store.create_pod(
        build_pod(name="watched", group_name="pg-w", req=build_resource_list(cpu=1, memory="1Gi"))
    )

    deadline = time.monotonic() + 15
    bound = False
    while time.monotonic() < deadline and not bound:
        payload = http_get_json(
            server, f"/apis/v1alpha1/watch/pods?since={since}&timeout=5"
        )
        for ev in payload["events"]:
            if ev["object"]["name"] == "watched" and ev["object"]["node"]:
                bound = True
        since = payload["resourceVersion"]
    assert bound, "watch never delivered the bind event"


def test_watch_gone_when_client_falls_behind():
    from kube_batch_tpu.cache import ClusterStore
    from kube_batch_tpu.server import WatchHub
    from kube_batch_tpu.testing import build_queue
    import threading

    store = ClusterStore()
    hub = WatchHub(store)
    for i in range(WatchHub.MAX_EVENTS + 10):
        store.create_queue(build_queue(f"q{i}"))
        store.delete_queue(f"q{i}")
    status, events, rv = hub.poll("queues", since=0, timeout=0, stop=threading.Event())
    assert status == "gone"
    status, events, _ = hub.poll("queues", since=rv, timeout=0, stop=threading.Event())
    assert status == "ok" and events == []


def test_watch_unknown_kind_404(server):
    url = f"http://127.0.0.1:{server.listen_port}/apis/v1alpha1/watch/gizmos"
    try:
        urllib.request.urlopen(url, timeout=5)
        assert False, "expected 404"
    except urllib.error.HTTPError as err:
        assert err.code == 404


def test_cli_queue_list_watch(server):
    """kbt-ctl queue list --watch streams the create event (kubectl -w
    shape): start the watcher, create a queue, see the ADDED line."""
    import io
    import threading

    from kube_batch_tpu.cli.queue import main as cli_main

    out = io.StringIO()
    done = threading.Event()

    def run_cli():
        cli_main(
            [
                "--server", f"http://127.0.0.1:{server.listen_port}",
                "queue", "list", "--watch", "--watch-once", "--watch-timeout", "10",
            ],
            out=out,
        )
        done.set()

    t = threading.Thread(target=run_cli, daemon=True)
    t.start()
    # The CLI prints the list header before entering the watch loop —
    # wait for it so the create's event lands after its resourceVersion.
    wait_until(lambda: "Name" in out.getvalue(), what="CLI initial list")
    server.store.create_queue(
        __import__("kube_batch_tpu.testing", fromlist=["build_queue"]).build_queue("streamed", weight=3)
    )
    assert done.wait(timeout=15), "CLI watch never returned"
    text = out.getvalue()
    assert "ADDED" in text and "streamed" in text, text


def test_add_flags_snapshot():
    """options_test.go:27 TestAddFlags — overriding one flag leaves every
    other option at its documented default."""
    from kube_batch_tpu.server import (
        DEFAULT_LISTEN_ADDRESS,
        DEFAULT_QUEUE,
        DEFAULT_SCHEDULER_NAME,
        build_parser,
    )

    opt = build_parser().parse_args(["--schedule-period", "300"])
    assert opt.schedule_period == 300.0
    assert opt.scheduler_name == DEFAULT_SCHEDULER_NAME
    assert opt.default_queue == DEFAULT_QUEUE
    assert opt.listen_address == DEFAULT_LISTEN_ADDRESS
    assert opt.scheduler_conf == "" and not opt.leader_elect and opt.v == 0


def test_select_best_node():
    """scheduler_helper_test.go:26 TestSelectBestNode — the highest score
    bucket wins (our pick inside the bucket is deterministic first-entry;
    the reference randomizes, so any bucket member is a valid answer)."""
    from kube_batch_tpu.api.node_info import NodeInfo
    from kube_batch_tpu.utils import select_best_node

    def node(name):
        n = NodeInfo()
        n.name = name
        return n

    n1, n2, n3, n4, n5 = (node(f"node{i}") for i in range(1, 6))
    assert select_best_node({1.0: [n1, n2], 2.0: [n3, n4]}) in (n3, n4)
    assert select_best_node({1.0: [n1, n2], 3.0: [n3], 2.0: [n4, n5]}) is n3
    assert select_best_node({}) is None
