"""InterPodAffinity priority: full k8s-1.13 symmetric-weight parity
(reference nodeorder.go:210-216 -> CalculateInterPodAffinityPriority) and
serial ≡ xla equivalence when interpod scores are live.
"""

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu import plugins  # noqa: F401
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.plugins.nodeorder import interpod_affinity_scores
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

TIERS_YAML = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def _node_info(name, labels=None, residents=()):
    node = build_node(name, build_resource_list(cpu=16, memory="32Gi", pods=20), labels=labels)
    ni = NodeInfo(node)
    for pod in residents:
        ni.add_task(TaskInfo(pod))
    return ni


def _running(name, labels=None, affinity=None, node_name="n0"):
    pod = build_pod(
        name=name,
        node_name=node_name,
        phase=PodPhase.RUNNING,
        req=build_resource_list(cpu=1, memory="1Gi"),
        labels=labels,
    )
    pod.affinity = affinity
    return pod


def test_incoming_preferred_affinity_scores_domain():
    """Incoming pod prefers co-location with app=web by zone: the zone
    hosting a web pod outranks the other; normalization is 0..10."""
    web = _running("web", labels={"app": "web"}, node_name="n0")
    nodes = {
        "n0": _node_info("n0", {"zone": "a"}, [web]),
        "n1": _node_info("n1", {"zone": "a"}),
        "n2": _node_info("n2", {"zone": "b"}),
    }
    task = TaskInfo(build_pod(name="in", req=build_resource_list(cpu=1, memory="1Gi")))
    task.pod.affinity = Affinity(
        pod_affinity_preferred=[(3, PodAffinityTerm({"app": "web"}, "zone"))]
    )
    scores = interpod_affinity_scores(task, nodes)
    # zone a (n0, n1) gets weight 3, zone b gets 0 -> normalized 10 vs 0
    assert scores == {"n0": 10, "n1": 10, "n2": 0}


def test_incoming_preferred_anti_affinity_penalizes_domain():
    web = _running("web", labels={"app": "web"}, node_name="n0")
    nodes = {
        "n0": _node_info("n0", {"zone": "a"}, [web]),
        "n1": _node_info("n1", {"zone": "b"}),
    }
    task = TaskInfo(build_pod(name="in", req=build_resource_list(cpu=1, memory="1Gi")))
    task.pod.affinity = Affinity(
        pod_anti_affinity_preferred=[(5, PodAffinityTerm({"app": "web"}, "zone"))]
    )
    scores = interpod_affinity_scores(task, nodes)
    assert scores == {"n0": 0, "n1": 10}  # -5 vs 0, min-max normalized


def test_symmetric_preferred_from_resident():
    """A resident pod PREFERS pods like the incoming one: the resident's
    term scores the incoming pod toward the resident's domain even though
    the incoming pod itself has no affinity at all."""
    lover = _running(
        "lover",
        labels={},
        affinity=Affinity(
            pod_affinity_preferred=[(7, PodAffinityTerm({"role": "friend"}, "kubernetes.io/hostname"))]
        ),
        node_name="n0",
    )
    nodes = {
        "n0": _node_info("n0", residents=[lover]),
        "n1": _node_info("n1"),
    }
    task = TaskInfo(
        build_pod(name="in", req=build_resource_list(cpu=1, memory="1Gi"), labels={"role": "friend"})
    )
    scores = interpod_affinity_scores(task, nodes)
    assert scores == {"n0": 10, "n1": 0}
    # a pod NOT matching the resident's selector gets nothing
    other = TaskInfo(build_pod(name="other", req=build_resource_list(cpu=1, memory="1Gi")))
    assert interpod_affinity_scores(other, nodes) == {"n0": 0, "n1": 0}


def test_hard_symmetric_weight_from_required_terms():
    """A resident's REQUIRED affinity terms toward the incoming pod score
    the hard symmetric weight (v1.DefaultHardPodAffinitySymmetricWeight)."""
    needy = _running(
        "needy",
        affinity=Affinity(
            pod_affinity_required=[PodAffinityTerm({"app": "db"}, "kubernetes.io/hostname")]
        ),
        node_name="n1",
    )
    nodes = {
        "n0": _node_info("n0"),
        "n1": _node_info("n1", residents=[needy]),
    }
    task = TaskInfo(
        build_pod(name="in", req=build_resource_list(cpu=1, memory="1Gi"), labels={"app": "db"})
    )
    assert interpod_affinity_scores(task, nodes) == {"n0": 0, "n1": 10}


def test_no_terms_anywhere_all_zero():
    nodes = {"n0": _node_info("n0", residents=[_running("r")]), "n1": _node_info("n1")}
    task = TaskInfo(build_pod(name="in", req=build_resource_list(cpu=1, memory="1Gi")))
    assert interpod_affinity_scores(task, nodes) == {"n0": 0, "n1": 0}


# -- serial ≡ xla with live interpod scores ----------------------------------


def run_and_capture(action_name, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(TIERS_YAML).tiers)
    get_action(action_name).execute(ssn)
    state = {}
    for job in ssn.jobs.values():
        for tasks in job.task_status_index.values():
            for t in tasks.values():
                state[t.uid] = (t.status, t.node_name)
    close_session(ssn)
    return state, dict(cache.binder.binds)


def assert_equivalent(make_cluster):
    s_state, s_binds = run_and_capture("allocate", make_cluster())
    x_state, x_binds = run_and_capture("xla_allocate", make_cluster())
    assert x_binds == s_binds
    assert x_state == s_state


def test_serial_equals_xla_resident_terms_shift_plain_tasks():
    """Residents with preferred terms give NON-affinity pending tasks
    nonzero interpod scores; the kernel's pod_sc matrix must reproduce
    the serial plugin's placements."""

    def mk():
        magnet = build_pod(
            name="magnet",
            node_name="n2",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=1, memory="1Gi"),
        )
        magnet.affinity = Affinity(
            pod_affinity_preferred=[(9, PodAffinityTerm({"tier": "app"}, "kubernetes.io/hostname"))]
        )
        pods = [magnet] + [
            build_pod(
                name=f"p{i}",
                group_name="pg",
                req=build_resource_list(cpu=1, memory="1Gi"),
                labels={"tier": "app"},
            )
            for i in range(3)
        ]
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=10))
            for i in range(4)
        ]
        return build_cluster(
            pods, nodes, [build_pod_group("pg", min_member=1)], [build_queue("default")]
        )

    # sanity: the serial path actually pulls tasks toward the magnet node
    _, binds = run_and_capture("allocate", mk())
    assert "n2" in binds.values()
    assert_equivalent(mk)


def test_serial_equals_xla_pending_preferred_terms_refresh():
    """Pending tasks carrying preferred terms step host-side and refresh
    pod_sc between kernel resumes: once the first lands, the second's
    preference for it must act — identically in both paths."""

    def mk():
        pods = []
        for i in range(2):
            pod = build_pod(
                name=f"pair{i}",
                group_name=f"pg{i}",
                req=build_resource_list(cpu=1, memory="1Gi"),
                labels={"pack": "yes"},
            )
            pod.affinity = Affinity(
                pod_affinity_preferred=[(8, PodAffinityTerm({"pack": "yes"}, "kubernetes.io/hostname"))]
            )
            pods.append(pod)
        pods.append(
            build_pod(name="plain", group_name="pg2", req=build_resource_list(cpu=1, memory="1Gi"))
        )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=10))
            for i in range(3)
        ]
        pgs = [build_pod_group(f"pg{i}", min_member=1) for i in range(3)]
        return build_cluster(pods, nodes, pgs, [build_queue("default")])

    assert_equivalent(mk)


def test_preempt_parity_with_interpod_active():
    """xla_preempt disables the vector scan when interpod is live and
    must still match the serial action exactly."""
    from test_xla_preempt import PREEMPT_TIERS

    def mk():
        victims = [
            build_pod(
                name=f"low{i}",
                group_name="low",
                req=build_resource_list(cpu=1, memory="512Mi"),
                node_name=f"n{i}",
                phase=PodPhase.RUNNING,
                priority=1,
                labels={"kind": "victim"},
            )
            for i in range(2)
        ]
        hi = build_pod(
            name="hi", group_name="hi", req=build_resource_list(cpu=1, memory="512Mi"), priority=9
        )
        hi.affinity = Affinity(
            pod_affinity_preferred=[(2, PodAffinityTerm({"kind": "victim"}, "kubernetes.io/hostname"))]
        )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=1, memory="1Gi", pods=5))
            for i in range(2)
        ]
        return build_cluster(
            victims + [hi],
            nodes,
            [build_pod_group("low", min_member=1), build_pod_group("hi", min_member=1)],
            [build_queue("default")],
        )

    def runp(action):
        cache = FakeCache(mk())
        ssn = open_session(cache, parse_scheduler_conf(PREEMPT_TIERS).tiers)
        get_action(action).execute(ssn)
        ev = list(cache.evictor.evicts)
        close_session(ssn)
        return ev

    assert runp("preempt") == runp("xla_preempt")
