"""xla_reclaim ≡ reclaim: the vectorized predicate walk must evict and
pipeline identically to the serial action (reclaim.go:54-186 parity)."""

import random

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu import plugins  # noqa: F401
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

RECLAIM_TIERS = """
actions: "reclaim"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_and_capture(action_name, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(RECLAIM_TIERS).tiers)
    get_action(action_name).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return state, list(cache.evictor.evicts)


def gen_reclaim_cluster(seed: int):
    """One queue hogging nodes past its deserved share, another starved —
    the proportion plugin's Reclaimable working set."""
    rng = random.Random(seed)
    n_nodes = rng.randint(2, 6)
    nodes = [
        build_node(f"n{i:02d}", build_resource_list(cpu=2, memory="2Gi", pods=8))
        for i in range(n_nodes)
    ]
    qa = build_queue("qa", weight=1)
    qb = build_queue("qb", weight=rng.randint(2, 5))
    qa.metadata.creation_timestamp = 0.0
    qb.metadata.creation_timestamp = 1.0

    pods, pgs = [], []
    # qa holds every slot
    slot = 0
    for j in range((2 * n_nodes + 3) // 4):
        name = f"hog{j}"
        pgs.append(build_pod_group(name, queue="qa", min_member=0))
        for t in range(4):
            if slot >= 2 * n_nodes:
                break
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=f"n{slot // 2:02d}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                )
            )
            slot += 1
    # qb starves
    for j in range(rng.randint(1, 3)):
        name = f"starved{j}"
        n_tasks = rng.randint(1, 3)
        pgs.append(build_pod_group(name, queue="qb", min_member=1))
        for t in range(n_tasks):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=rng.choice([1, 5]),
                )
            )
    return build_cluster(pods, nodes, pgs, [qa, qb])


def test_cross_queue_reclaim_parity():
    s_state, s_ev = run_and_capture("reclaim", gen_reclaim_cluster(1))
    x_state, x_ev = run_and_capture("xla_reclaim", gen_reclaim_cluster(1))
    assert len(x_ev) >= 1  # the scene must actually reclaim something
    assert x_ev == s_ev
    assert x_state == s_state


def test_property_reclaim_parity():
    for seed in range(16):
        s = run_and_capture("reclaim", gen_reclaim_cluster(seed))
        x = run_and_capture("xla_reclaim", gen_reclaim_cluster(seed))
        assert x == s, f"seed {seed} diverged"
