"""xla_reclaim ≡ reclaim: the vectorized predicate walk must evict and
pipeline identically to the serial action (reclaim.go:54-186 parity)."""

import random

from kube_batch_tpu import actions  # noqa: F401
from kube_batch_tpu import plugins  # noqa: F401
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

RECLAIM_TIERS = """
actions: "reclaim"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_and_capture(action_name, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(RECLAIM_TIERS).tiers)
    get_action(action_name).execute(ssn)
    state = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return state, list(cache.evictor.evicts)


def gen_reclaim_cluster(seed: int):
    """One queue hogging nodes past its deserved share, another starved —
    the proportion plugin's Reclaimable working set."""
    rng = random.Random(seed)
    n_nodes = rng.randint(2, 6)
    nodes = [
        build_node(f"n{i:02d}", build_resource_list(cpu=2, memory="2Gi", pods=8))
        for i in range(n_nodes)
    ]
    qa = build_queue("qa", weight=1)
    qb = build_queue("qb", weight=rng.randint(2, 5))
    qa.metadata.creation_timestamp = 0.0
    qb.metadata.creation_timestamp = 1.0

    pods, pgs = [], []
    # qa holds every slot
    slot = 0
    for j in range((2 * n_nodes + 3) // 4):
        name = f"hog{j}"
        pgs.append(build_pod_group(name, queue="qa", min_member=0))
        for t in range(4):
            if slot >= 2 * n_nodes:
                break
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=f"n{slot // 2:02d}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                )
            )
            slot += 1
    # qb starves
    for j in range(rng.randint(1, 3)):
        name = f"starved{j}"
        n_tasks = rng.randint(1, 3)
        pgs.append(build_pod_group(name, queue="qb", min_member=1))
        for t in range(n_tasks):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=rng.choice([1, 5]),
                )
            )
    return build_cluster(pods, nodes, pgs, [qa, qb])


def test_cross_queue_reclaim_parity():
    s_state, s_ev = run_and_capture("reclaim", gen_reclaim_cluster(1))
    x_state, x_ev = run_and_capture("xla_reclaim", gen_reclaim_cluster(1))
    assert len(x_ev) >= 1  # the scene must actually reclaim something
    assert x_ev == s_ev
    assert x_state == s_state


def test_property_reclaim_parity():
    for seed in range(16):
        s = run_and_capture("reclaim", gen_reclaim_cluster(seed))
        x = run_and_capture("xla_reclaim", gen_reclaim_cluster(seed))
        assert x == s, f"seed {seed} diverged"


def gen_contended_reclaim_cluster(seed: int):
    """Richer randomized multi-queue scene (VERDICT r3 item 5: mirror
    test_xla_preempt's contended sweep): 2-4 queues with random weights,
    randomly distributed running hogs of varied sizes, node selectors on
    some starved pods, mixed priorities and gang minimums."""
    rng = random.Random(10_000 + seed)
    n_queues = rng.randint(2, 4)
    queues = [build_queue(f"q{i}", weight=rng.randint(1, 5)) for i in range(n_queues)]
    for i, q in enumerate(queues):
        q.metadata.creation_timestamp = float(i)

    nodes = []
    n_nodes = rng.randint(3, 8)
    for i in range(n_nodes):
        labels = {"zone": rng.choice(["a", "b"])} if rng.random() < 0.3 else {}
        nodes.append(
            build_node(
                f"n{i:02d}",
                build_resource_list(cpu=4, memory="4Gi", pods=rng.randint(4, 10)),
                labels=labels,
            )
        )

    pods, pgs = [], []
    # over-served queues: the first 1-2 queues hog most slots with
    # variously sized running pods
    hog_queues = queues[: rng.randint(1, 2)]
    free = {n.name: 4 for n in nodes}
    for j in range(rng.randint(2, 4)):
        name = f"hog{j}"
        pgs.append(
            build_pod_group(
                name,
                queue=rng.choice(hog_queues).name,
                min_member=rng.randint(0, 1),
            )
        )
        for t in range(rng.randint(2, 5)):
            hosts = [n for n, f in free.items() if f >= 1]
            if not hosts:
                break
            host = rng.choice(hosts)
            cpu = rng.choice([1, 2])
            if free[host] < cpu:
                cpu = 1
            free[host] -= cpu
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=host,
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=cpu, memory=f"{cpu}Gi"),
                    priority=rng.choice([0, 1]),
                )
            )

    # under-served queues starve with pending work
    for j, q in enumerate(queues[len(hog_queues):]):
        for k in range(rng.randint(1, 2)):
            name = f"starved{j}-{k}"
            n_tasks = rng.randint(1, 3)
            pgs.append(
                build_pod_group(name, queue=q.name, min_member=rng.randint(1, n_tasks))
            )
            for t in range(n_tasks):
                pod = build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu=rng.choice([1, 2]), memory=rng.choice(["512Mi", "1Gi", "2Gi"])
                    ),
                    priority=rng.choice([1, 5, 9]),
                )
                if rng.random() < 0.2:
                    pod.node_selector = {"zone": rng.choice(["a", "b"])}
                pods.append(pod)

    return build_cluster(pods, nodes, pgs, queues)


def test_property_contended_reclaim_parity():
    """24-seed randomized contended parity (the xla_preempt sweep's
    twin): identical evict lists and identical full session state."""
    reclaimed = 0
    for seed in range(24):
        s_state, s_ev = run_and_capture("reclaim", gen_contended_reclaim_cluster(seed))
        x_state, x_ev = run_and_capture("xla_reclaim", gen_contended_reclaim_cluster(seed))
        assert x_ev == s_ev, f"seed {seed} evict divergence"
        assert x_state == s_state, f"seed {seed} state divergence"
        reclaimed += len(s_ev)
    assert reclaimed >= 10, f"sweep too tame to prove anything ({reclaimed} evicts)"
