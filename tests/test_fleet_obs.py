"""Tier-1 tests for the fleet observatory (PR 14): mergeable
QuantileSketch property checks against pooled-raw ground truth, the
SLO accountant's LRU cardinality bound, the KBT_FLEET off-switch
discipline, an in-process scrape->merge aggregator drill, OpenMetrics
exemplar gating, the KBT-R012 SLO-kind registry analyzer, and the
bench_diff handling of device-phase telemetry columns.

The heavyweight end-to-end proof (N live loopback shards over the real
federation wire path) lives in ``python -m kube_batch_tpu.obs.fleet``
and runs as hack/verify.py's ``fleet_obs_smoke`` gate; these tests pin
the component contracts that smoke composes.
"""

from __future__ import annotations

import ast
import importlib.util
import json
import math
import os
import random
import time

import numpy as np
import pytest

from kube_batch_tpu import metrics
from kube_batch_tpu import obs
from kube_batch_tpu import pipeline
from kube_batch_tpu.analysis import SourceFile
from kube_batch_tpu.analysis import registry_consistency
from kube_batch_tpu.obs import QuantileSketch, SLOAccountant
from kube_batch_tpu.obs import fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the bound every sketch consumer (smoke, verify gate, these tests)
# holds quantiles to: declared alpha plus a 5% margin for the bucket
# midpoint sitting a hair past the ideal reconstruction
REL_BOUND = QuantileSketch.DEFAULT_ALPHA * 1.05


def _nearest_rank(sorted_values: list[float], q: float) -> float:
    n = len(sorted_values)
    return sorted_values[min(n - 1, max(0, math.ceil(q * n) - 1))]


def _fill(sk: QuantileSketch, values, t0: float) -> None:
    # deterministic timestamps well inside the window, spread over a
    # few slices so the ring (not just one slice) is exercised
    for i, v in enumerate(values):
        sk.add(v, t=t0 + (i % 7) * sk.slice_s * 0.9)


def _assert_wire_equal(a: dict, b: dict) -> None:
    """Cell-for-cell wire equality; the per-slice running sum ``s`` is
    compared approximately (float addition order differs between a
    pooled stream and a merge fold)."""
    assert a["alpha"] == b["alpha"] and a["slice_s"] == b["slice_s"]
    assert sorted(a["slices"]) == sorted(b["slices"])
    for epoch, sa in a["slices"].items():
        sb = b["slices"][epoch]
        assert sa["b"] == sb["b"] and sa["z"] == sb["z"] and sa["n"] == sb["n"]
        assert sa["s"] == pytest.approx(sb["s"])


# -- sketch properties -------------------------------------------------------


@pytest.mark.parametrize("dist", ["uniform", "lognormal", "exponential"])
@pytest.mark.parametrize("seed", [1, 7])
def test_sketch_quantiles_within_declared_relative_error(dist, seed):
    rng = random.Random(seed)
    if dist == "uniform":
        values = [rng.uniform(0.001, 10.0) for _ in range(2000)]
    elif dist == "lognormal":
        values = [rng.lognormvariate(0.0, 1.5) for _ in range(2000)]
    else:
        values = [rng.expovariate(4.0) for _ in range(2000)]
    sk = QuantileSketch(window_s=300.0)
    _fill(sk, values, time.time())
    ordered = sorted(values)
    assert sk.count() == len(values)
    assert sk.total() == pytest.approx(sum(values))
    for q in (0.25, 0.5, 0.9, 0.99):
        exact = _nearest_rank(ordered, q)
        got = sk.quantile(q)
        assert got == pytest.approx(exact, rel=REL_BOUND), (dist, q)


def test_sketch_merge_equals_pooled_sketch_exactly():
    """The tentpole invariant: N shards' sketches merged cell-wise are
    identical (counts, totals, every quantile) to ONE sketch fed the
    pooled stream — not merely within tolerance."""
    rng = random.Random(42)
    values = [rng.lognormvariate(-1.0, 1.0) for _ in range(1500)]
    t0 = time.time()
    pooled = QuantileSketch(window_s=300.0)
    _fill(pooled, values, t0)
    shards = [QuantileSketch(window_s=300.0) for _ in range(3)]
    for i, v in enumerate(values):
        # same timestamp function of i as _fill, routed round-robin
        shards[i % 3].add(v, t=t0 + (i % 7) * pooled.slice_s * 0.9)
    merged = QuantileSketch(window_s=300.0)
    for sh in shards:
        merged.merge(sh)
    assert merged.count() == pooled.count() == len(values)
    assert merged.total() == pytest.approx(pooled.total())
    for q in (0.01, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0):
        assert merged.quantile(q) == pooled.quantile(q)
    # and the wire forms agree cell-for-cell
    _assert_wire_equal(merged.to_wire(), pooled.to_wire())


def test_sketch_merge_order_independent():
    rng = random.Random(3)
    t0 = time.time()
    parts = []
    for _ in range(4):
        sk = QuantileSketch(window_s=300.0)
        _fill(sk, [rng.expovariate(2.0) for _ in range(200)], t0)
        parts.append(sk)
    fwd = QuantileSketch(window_s=300.0)
    rev = QuantileSketch(window_s=300.0)
    for sk in parts:
        fwd.merge(sk)
    for sk in reversed(parts):
        rev.merge(sk)
    _assert_wire_equal(fwd.to_wire(), rev.to_wire())


def test_sketch_empty_and_singleton_edges():
    sk = QuantileSketch(window_s=300.0)
    assert sk.count() == 0
    assert sk.quantile(0.5) == 0.0
    assert sk.quantile(0.99) == 0.0
    one = QuantileSketch(window_s=300.0)
    one.add(0.125, t=time.time())
    assert one.count() == 1
    for q in (0.0, 0.5, 1.0):
        assert one.quantile(q) == pytest.approx(0.125, rel=REL_BOUND)
    # merging an empty sketch is the identity
    before = one.to_wire()
    one.merge(sk)
    assert one.to_wire() == before


def test_sketch_zero_bucket_and_expiry():
    sk = QuantileSketch(window_s=0.06, slices=3)
    now = time.time()
    sk.add(0.0, t=now)  # below _SKETCH_MIN -> zero bucket
    sk.add(1.0, t=now)
    assert sk.count() == 2
    assert sk.quantile(0.25) == 0.0
    assert sk.quantile(1.0) == pytest.approx(1.0, rel=REL_BOUND)
    sk.trim(now + 1.0)  # whole window expired -> every slice dropped
    assert sk.count() == 0
    assert sk.quantile(0.5) == 0.0


def test_sketch_wire_round_trip_then_merge():
    rng = random.Random(11)
    t0 = time.time()
    a = QuantileSketch(window_s=300.0)
    b = QuantileSketch(window_s=300.0)
    _fill(a, [rng.uniform(0.01, 2.0) for _ in range(300)], t0)
    _fill(b, [rng.uniform(0.01, 2.0) for _ in range(300)], t0)
    # the exact /debug/slo?raw=1 path: serialize -> JSON -> deserialize
    a2 = QuantileSketch.from_wire(json.loads(json.dumps(a.to_wire())))
    b2 = QuantileSketch.from_wire(json.loads(json.dumps(b.to_wire())))
    assert a2.to_wire() == a.to_wire()
    direct = QuantileSketch(window_s=300.0).merge(a).merge(b)
    rehydrated = QuantileSketch(window_s=300.0).merge(a2).merge(b2)
    assert rehydrated.to_wire() == direct.to_wire()
    for q in (0.5, 0.9, 0.99):
        assert rehydrated.quantile(q) == direct.quantile(q)


def test_sketch_merge_rejects_mismatched_geometry():
    base = QuantileSketch(alpha=0.01, window_s=300.0)
    with pytest.raises(ValueError, match="alpha"):
        base.merge(QuantileSketch(alpha=0.02, window_s=300.0))
    with pytest.raises(ValueError, match="slice_s"):
        base.merge(QuantileSketch(alpha=0.01, window_s=60.0))


# -- LRU cardinality bound ---------------------------------------------------


def test_slo_accountant_lru_bounds_queue_cardinality():
    acct = SLOAccountant(window_s=300.0, max_queues=4)
    evicted_before = metrics.slo_evicted_queues.value()
    # seed a gauge series for the first queue so eviction can drop it
    metrics.set_slo_quantile("time_to_bind", "q0", "p50", 0.5)
    for i in range(10):
        acct.observe("time_to_bind", f"q{i}", 0.1)
    snap = acct.snapshot()
    assert sorted(snap["time_to_bind"]) == ["q6", "q7", "q8", "q9"]
    assert metrics.slo_evicted_queues.value() - evicted_before == 6
    # the evicted queue's label set left the gauge too
    assert (
        ("queue", "q0"),
        ("quantile", "p50"),
    ) not in metrics.slo_time_to_bind.samples()


def test_slo_accountant_lru_touch_protects_hot_queue():
    acct = SLOAccountant(window_s=300.0, max_queues=2)
    acct.observe("time_to_bind", "hot", 0.1)
    acct.observe("time_to_bind", "cold", 0.1)
    acct.observe("time_to_bind", "hot", 0.2)  # re-touch: hot moves newest
    acct.observe("time_to_bind", "new", 0.1)  # evicts cold, not hot
    assert sorted(acct.snapshot()["time_to_bind"]) == ["hot", "new"]


# -- KBT_FLEET off-switch discipline -----------------------------------------


def test_fleet_off_is_identity_noop(monkeypatch):
    monkeypatch.delenv(fleet.ENV, raising=False)
    fleet.configure()
    assert not fleet.enabled()
    assert fleet.refresh() is fleet.NOOP_PAYLOAD
    assert fleet.refresh(force=True) is fleet.NOOP_PAYLOAD


def test_fleet_off_overhead_is_one_branch(monkeypatch):
    """Same discipline (and budget) as obs' KBT_TRACE off-guard: the
    disabled refresh must be a bool check returning a shared dict."""
    monkeypatch.delenv(fleet.ENV, raising=False)
    fleet.configure()
    n = 20_000
    for _ in range(1000):  # warmup
        fleet.refresh()
    start = time.perf_counter()
    for _ in range(n):
        fleet.refresh()
    off_cost = (time.perf_counter() - start) / n
    assert off_cost < 5e-5, f"disabled fleet.refresh() costs {off_cost:.2e}s/call"


# -- in-process scrape -> merge drill ----------------------------------------


def test_fleet_aggregator_merges_loopback_shards():
    """Two loopback observatories (the smoke's stand-in for peer
    shards' /debug/slo?raw=1), scraped over real HTTP by a fresh
    FleetAggregator: merged quantiles match pooled raw samples, the
    conflict heatmap ranks delta'd nodes, and the fleet gauges land."""
    acct_a = SLOAccountant(window_s=300.0)
    acct_b = SLOAccountant(window_s=300.0)
    for v in (0.1, 0.2, 0.3):
        acct_a.observe("time_to_bind", "tenant0", v)
    for v in (0.4, 0.5):
        acct_b.observe("time_to_bind", "tenant0", v)
    acct_b.observe("time_to_bind", "tenant1", 1.0)

    def _counters_a():
        return {
            "federation_conflicts": {},
            "node_conflicts": {"node-a": 3.0},
            "streaming_backlog": 4,
            "binds_total": 10,
        }

    def _counters_b():
        return {
            "federation_conflicts": {},
            "node_conflicts": {"node-a": 1.0, "node-b": 2.0},
            "streaming_backlog": 6,
            "binds_total": 20,
        }

    srv_a, th_a = fleet._serve_observatory(acct_a, _counters_a)
    srv_b, th_b = fleet._serve_observatory(acct_b, _counters_b)
    urls = [
        f"http://127.0.0.1:{srv_a.server_address[1]}",
        f"http://127.0.0.1:{srv_b.server_address[1]}",
    ]
    prev = os.environ.get(fleet.ENV)
    os.environ[fleet.ENV] = ",".join(urls)
    try:
        fleet.configure()
        agg = fleet.FleetAggregator()
        payload = agg.refresh(force=True)
    finally:
        if prev is None:
            os.environ.pop(fleet.ENV, None)
        else:
            os.environ[fleet.ENV] = prev
        fleet.configure()
        for srv, th in ((srv_a, th_a), (srv_b, th_b)):
            srv.shutdown()
            srv.server_close()
            th.join(timeout=5.0)

    assert payload["enabled"] is True
    assert payload["shards_scraped"] == 2
    t0_stats = payload["slo"]["time_to_bind"]["tenant0"]
    assert t0_stats["n"] == 5
    assert t0_stats["p50"] == pytest.approx(0.3, rel=REL_BOUND)
    assert t0_stats["p99"] == pytest.approx(0.5, rel=REL_BOUND)
    t1_stats = payload["slo"]["time_to_bind"]["tenant1"]
    assert t1_stats["n"] == 1
    assert t1_stats["p50"] == pytest.approx(1.0, rel=REL_BOUND)
    # first scrape: deltas against an empty baseline are the totals
    assert payload["node_conflict_topk"] == {"node-a": 4.0, "node-b": 2.0}
    assert payload["backlog_pods"] == 10.0
    # the cluster-wide gauges carry the same numbers
    assert metrics.fleet_shards_scraped.value() == 2
    assert metrics.fleet_backlog.value() == 10.0
    assert metrics.fleet_node_conflicts.value({"node": "node-a"}) == 4.0
    assert metrics.fleet_slo_time_to_bind.value(
        {"queue": "tenant0", "quantile": "p50"}
    ) == pytest.approx(0.3, rel=REL_BOUND)


def test_fleet_aggregator_counts_dark_shards():
    prev = os.environ.get(fleet.ENV)
    # nothing listens on this port: the scrape fails, the aggregator
    # still publishes (shards_scraped=0), and nothing raises
    os.environ[fleet.ENV] = "http://127.0.0.1:9"
    try:
        fleet.configure()
        agg = fleet.FleetAggregator()
        payload = agg.refresh(force=True)
    finally:
        if prev is None:
            os.environ.pop(fleet.ENV, None)
        else:
            os.environ[fleet.ENV] = prev
        fleet.configure()
    assert payload["enabled"] is True
    assert payload["shards_scraped"] == 0
    assert payload["slo"] == {}


def test_fleet_refresh_bounded_by_hung_peer_not_stalled(monkeypatch):
    """A peer whose socket accepts but never answers (half-dead kernel,
    wedged shard) must cost one scrape timeout, not hang the refresh:
    the live shard's data still merges, the hung peer reads shard_up
    False, and the whole refresh returns within timeout + join slack.
    The admission BackpressureController reads this payload on its
    control loop — an unbounded refresh would freeze overload response
    exactly when a shard is sickest."""
    import socket

    # kernel completes the TCP handshake for a listening socket even
    # without accept(): urlopen connects fine, then waits forever for
    # the response — the exact half-dead shape a crashed-but-not-reaped
    # shard presents
    hung = socket.socket()
    hung.bind(("127.0.0.1", 0))
    hung.listen(1)

    acct = SLOAccountant(window_s=300.0)
    acct.observe("time_to_bind", "tenant0", 0.25)
    srv, th = fleet._serve_observatory(
        acct, lambda: {"federation_conflicts": {}, "node_conflicts": {},
                       "streaming_backlog": 0, "binds_total": 1},
    )
    live_url = f"http://127.0.0.1:{srv.server_address[1]}"
    hung_url = f"http://127.0.0.1:{hung.getsockname()[1]}"
    monkeypatch.setenv(fleet.ENV, f"{live_url},{hung_url}")
    monkeypatch.setenv(fleet.TIMEOUT_ENV, "0.2")
    try:
        fleet.configure()
        agg = fleet.FleetAggregator()
        t0 = time.monotonic()
        payload = agg.refresh(force=True)
        elapsed = time.monotonic() - t0
    finally:
        monkeypatch.delenv(fleet.ENV, raising=False)
        monkeypatch.delenv(fleet.TIMEOUT_ENV, raising=False)
        fleet.configure()
        srv.shutdown()
        srv.server_close()
        th.join(timeout=5.0)
        hung.close()
    # bound: per-peer timeout (scrapes run concurrently) + 1s join slack
    assert elapsed < 2.5, f"refresh stalled {elapsed:.2f}s behind a hung peer"
    assert payload["shards_scraped"] == 1
    assert payload["shard_up"][live_url] is True
    assert payload["shard_up"][hung_url] is False
    assert payload["slo"]["time_to_bind"]["tenant0"]["n"] == 1
    assert metrics.fleet_shard_up.value({"shard": hung_url}) == 0.0


# -- OpenMetrics exemplars ---------------------------------------------------


def test_exemplars_off_by_default(monkeypatch):
    monkeypatch.delenv(metrics.EXEMPLARS_ENV, raising=False)
    c = metrics.Counter("t_exemplar_off_total", "test counter")
    c.inc({"outcome": "won"}, exemplar="deadbeef")
    text = "\n".join(metrics._render_family(c))
    assert "deadbeef" not in text
    assert " # {" not in text


def test_exemplar_rides_counter_sample_line(monkeypatch):
    monkeypatch.setenv(metrics.EXEMPLARS_ENV, "1")
    c = metrics.Counter("t_exemplar_counter_total", "test counter")
    c.inc({"outcome": "won"}, exemplar="abc123")
    lines = metrics._render_family(c)
    sample = [l for l in lines if l.startswith("t_exemplar_counter_total{")]
    assert len(sample) == 1
    assert sample[0].endswith('# {trace_id="abc123"} 1.0')


def test_exemplar_rides_lowest_containing_histogram_bucket(monkeypatch):
    monkeypatch.setenv(metrics.EXEMPLARS_ENV, "1")
    h = metrics.Histogram("t_exemplar_hist", "test histogram", (0.1, 1.0))
    h.observe(0.5, exemplar="feedface")
    lines = metrics._render_family(h)
    marked = [l for l in lines if "feedface" in l]
    assert len(marked) == 1  # exactly one bucket carries it
    assert 'le="1.0"' in marked[0]  # the lowest bucket containing 0.5
    assert '# {trace_id="feedface"} 0.5' in marked[0]


def test_exemplar_storage_gated_like_rendering(monkeypatch):
    # observed while off, then rendered while on: nothing stale leaks
    monkeypatch.delenv(metrics.EXEMPLARS_ENV, raising=False)
    c = metrics.Counter("t_exemplar_gate_total", "test counter")
    c.inc(exemplar="ghost")
    monkeypatch.setenv(metrics.EXEMPLARS_ENV, "1")
    assert "ghost" not in "\n".join(metrics._render_family(c))


# -- KBT-R012: SLO kind registry ---------------------------------------------


def sf(path: str, source: str) -> SourceFile:
    return SourceFile(path, source, ast.parse(source, path))


R012_OBS = """
class SLOAccountant:
    KINDS = ("time_to_bind", "queue_wait", "ghost")
"""

R012_METRICS = """
_SLO_GAUGES = {
    "time_to_bind": slo_time_to_bind,
    "queue_wait": slo_queue_wait,
    "orphan": slo_orphan,
}
_FLEET_SLO_GAUGES = {
    "time_to_bind": fleet_slo_time_to_bind,
    "queue_wait": fleet_slo_queue_wait,
}
"""


def test_registry_slo_kinds_both_directions():
    files = [
        sf(registry_consistency.OBS_MODULE, R012_OBS),
        sf(registry_consistency.METRICS_MODULE, R012_METRICS),
    ]
    findings = []
    registry_consistency._check_slo_kind_registry(files, findings)
    assert all(f.code == "KBT-R012" for f in findings)
    syms = sorted((f.symbol, f.path) for f in findings)
    # "ghost" is a kind with no gauge entry in EITHER map (two findings,
    # anchored on obs); "orphan" is a gauge key that is not a kind
    # (anchored on metrics)
    assert syms == [
        ("slo_kind:ghost", registry_consistency.OBS_MODULE),
        ("slo_kind:ghost", registry_consistency.OBS_MODULE),
        ("slo_kind:orphan", registry_consistency.METRICS_MODULE),
    ]


def test_registry_slo_kinds_compliant_is_clean():
    files = [
        sf(
            registry_consistency.OBS_MODULE,
            'class SLOAccountant:\n    KINDS = ("time_to_bind", "queue_wait")\n',
        ),
        sf(
            registry_consistency.METRICS_MODULE,
            '_SLO_GAUGES = {"time_to_bind": a, "queue_wait": b}\n'
            '_FLEET_SLO_GAUGES = {"time_to_bind": c, "queue_wait": d}\n',
        ),
    ]
    findings = []
    registry_consistency._check_slo_kind_registry(files, findings)
    assert findings == []


def test_live_tree_slo_kind_registry_is_consistent():
    kinds = tuple(obs.SLOAccountant.KINDS)
    assert tuple(metrics._SLO_GAUGES) == kinds
    assert tuple(metrics._FLEET_SLO_GAUGES) == kinds


# -- bench_diff: device-phase columns are informational ----------------------


def _bench_diff_mod():
    spec = importlib.util.spec_from_file_location(
        "kbt_hack_bench_diff", os.path.join(REPO, "hack", "bench_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_diff_never_flags_device_phase_columns():
    bd = _bench_diff_mod()
    old = {"sched/2000x300": {
        "p50_s": 0.100, "solve_device_s": 0.020,
        "pipeline_overlap_fraction": 0.9,
        "arena_hbm_watermark_bytes": 1000, "fleet_shards": 2,
    }}
    new = {"sched/2000x300": {
        "p50_s": 0.101, "solve_device_s": 0.080,  # 4x: still only info
        "pipeline_overlap_fraction": 0.1,
        "arena_hbm_watermark_bytes": 9000, "fleet_shards": 4,
    }}
    summary = bd.diff_rows(old, new, threshold=0.15)
    assert summary["ok"] is True
    assert summary["findings"] == []
    assert len(summary["info"]) == 4
    assert any("solve_device_s 0.02 -> 0.08" in l for l in summary["info"])
    assert any("fleet_shards 2 -> 4" in l for l in summary["info"])


def test_bench_diff_info_does_not_mask_real_regression():
    bd = _bench_diff_mod()
    old = {"row": {"p50_s": 0.100, "solve_device_s": 0.020}}
    new = {"row": {"p50_s": 0.200, "solve_device_s": 0.021}}
    summary = bd.diff_rows(old, new, threshold=0.15)
    assert summary["ok"] is False
    assert [f["kind"] for f in summary["findings"]] == ["regression"]
    assert len(summary["info"]) == 1


# -- bench_diff: wire-transport columns gate (ISSUE 17) ----------------------


def _wire_run(proto, shards, binds_per_s, bytes_per_bind, rtt, batch_mean):
    return {
        "protocol": proto, "shards": shards, "binds_per_s": binds_per_s,
        "wire_bytes_per_bind": bytes_per_bind, "backend_rtt_p50_s": rtt,
        "txn_batch_mean": batch_mean, "exactly_once": True,
        "union_parity": True,
    }


def test_bench_diff_catches_v2_throughput_slide_back_to_v1():
    """The wire ladder's whole point: a v2 cell whose throughput slid
    back to v1 numbers (and whose byte volume grew back) must be a
    regression finding in the expanded pseudo-row — flagged, not an
    [info] line — even though the parent row's p50 is unchanged."""
    bd = _bench_diff_mod()
    old = {"federation_scaleout_50k": {
        "p50_s": 5.0,
        "wire_runs": [
            _wire_run(1, 4, 60.0, 15000.0, 0.009, 0.0),
            _wire_run(2, 4, 85.0, 8600.0, 0.007, 50.0),
        ],
    }}
    # v2 n4 collapses to v1-grade throughput/bytes; coalescing depth
    # falls to per-gang (txn_batch_mean 50 -> 1); rtt doubles
    new = {"federation_scaleout_50k": {
        "p50_s": 5.0,
        "wire_runs": [
            _wire_run(1, 4, 60.0, 15000.0, 0.009, 0.0),
            _wire_run(2, 4, 41.0, 14800.0, 0.014, 1.0),
        ],
    }}
    summary = bd.diff_rows(
        bd._expand_wire_rows(old), bd._expand_wire_rows(new), threshold=0.15
    )
    assert summary["ok"] is False
    v2_findings = [
        f for f in summary["findings"]
        if f["row"] == "federation_scaleout_50k.wire_v2_n4"
    ]
    assert {f["kind"] for f in v2_findings} == {"regression"}
    flagged = " ".join(f["msg"] for f in v2_findings)
    assert "binds_per_s" in flagged          # higher-is-better shrank
    assert "wire_bytes_per_bind" in flagged  # lower-is-better grew
    assert "backend_rtt_p50_s" in flagged
    assert "txn_batch_mean" in flagged
    # the untouched v1 twin cell stays quiet
    assert not any(
        f["row"] == "federation_scaleout_50k.wire_v1_n4"
        for f in summary["findings"]
    )


def test_bench_diff_wire_parity_bits_and_improvements():
    bd = _bench_diff_mod()
    old = {"fed": {"wire_runs": [_wire_run(2, 8, 50.0, 16000.0, 0.01, 25.0)]}}
    better = {"fed": {"wire_runs": [_wire_run(2, 8, 70.0, 9000.0, 0.006, 25.0)]}}
    summary = bd.diff_rows(
        bd._expand_wire_rows(old), bd._expand_wire_rows(better), threshold=0.15
    )
    assert summary["ok"] is True and summary["findings"] == []
    assert any("binds_per_s" in line for line in summary["improvements"])
    # a correctness bit going false is a parity finding no number excuses
    broken = {"fed": {"wire_runs": [
        dict(_wire_run(2, 8, 70.0, 9000.0, 0.006, 25.0), exactly_once=False)
    ]}}
    summary = bd.diff_rows(
        bd._expand_wire_rows(old), bd._expand_wire_rows(broken), threshold=0.15
    )
    assert summary["ok"] is False
    assert any(
        f["kind"] == "parity" and "exactly_once" in f["msg"]
        for f in summary["findings"]
    )


# -- bench_diff: admission-storm columns gate directionally (ISSUE 18) -------


def _storm_row(p99, mttr, goodput, shed_low=20, exactly_once=True):
    return {"admission_storm": {
        "storm_high_p99_s": p99, "storm_mttr_s": mttr,
        "storm_goodput_pods_per_s": goodput, "storm_shed_high": 0,
        "storm_shed_low": shed_low, "exactly_once": exactly_once,
    }}


def test_bench_diff_storm_columns_gate_with_direction():
    bd = _bench_diff_mod()
    old = _storm_row(p99=0.9, mttr=1.5, goodput=30.0)
    # protected-lane tail and MTTR growing, goodput shrinking: three
    # regressions, each in its own direction
    worse = _storm_row(p99=1.8, mttr=2.5, goodput=20.0)
    summary = bd.diff_rows(old, worse, threshold=0.15)
    assert summary["ok"] is False
    msgs = [f["msg"] for f in summary["findings"]]
    assert any("storm_high_p99_s" in m and "lower-is-better" in m for m in msgs)
    assert any("storm_mttr_s" in m and "lower-is-better" in m for m in msgs)
    assert any(
        "storm_goodput_pods_per_s" in m and "higher-is-better" in m
        for m in msgs
    )
    # the same deltas in the healthy direction are improvements
    summary = bd.diff_rows(worse, old, threshold=0.15)
    assert summary["ok"] is True and len(summary["improvements"]) == 3


def test_bench_diff_storm_shed_counts_are_info_not_findings():
    bd = _bench_diff_mod()
    old = _storm_row(p99=0.9, mttr=1.5, goodput=30.0, shed_low=20)
    new = _storm_row(p99=0.9, mttr=1.5, goodput=30.0, shed_low=80)
    summary = bd.diff_rows(old, new, threshold=0.15)
    assert summary["ok"] is True and summary["findings"] == []
    assert any("storm_shed_low 20 -> 80" in line for line in summary["info"])
    # but the exactly_once bit flipping is a parity finding, not info
    broken = _storm_row(p99=0.9, mttr=1.5, goodput=30.0, exactly_once=False)
    summary = bd.diff_rows(old, broken, threshold=0.15)
    assert summary["ok"] is False
    assert [f["kind"] for f in summary["findings"]] == ["parity"]


# -- measured pipeline overlap -----------------------------------------------


def test_fence_overlap_fraction_is_measured_from_windows():
    fence = pipeline.DispatchFence()
    # dispatch spans [10, 12]; the join blocks over [11, 13]: second
    # half of the dispatch was hidden behind the consumer's wait -> 0.5
    fence.record_dispatch_window(10.0, 12.0)
    fence.record_join(11.0, 13.0)
    assert fence.last_overlap_fraction == pytest.approx(0.5)
    # one sample per dispatch window: a second join does not overwrite
    fence.record_join(10.0, 14.0)
    assert fence.last_overlap_fraction == pytest.approx(0.5)
    # a join that never touched the window: full overlap
    fence.record_dispatch_window(20.0, 22.0)
    fence.record_join(23.0, 24.0)
    assert fence.last_overlap_fraction == pytest.approx(1.0)
    # a join covering the whole window: fully serialized
    fence.record_dispatch_window(30.0, 32.0)
    fence.record_join(29.0, 33.0)
    assert fence.last_overlap_fraction == pytest.approx(0.0)
    fence.reset()
    assert fence.last_overlap_fraction is None


# -- arena HBM accounting ----------------------------------------------------


def test_arena_accounts_hbm_bytes_and_watermark():
    from kube_batch_tpu.ops.encode_cache import TensorArena

    arena = TensorArena()
    arrays = {
        "node_idle": np.ones((8, 4), dtype=np.float32),
        "task_req": np.ones((3, 4), dtype=np.float32),
    }
    arena.device_view(arrays)
    by_slab = arena.hbm_bytes_by_slab()
    assert by_slab["node_idle"] == 8 * 4 * 4
    assert by_slab["task_req"] == 3 * 4 * 4
    total = sum(by_slab.values())
    assert arena.hbm_watermark_bytes == total
    assert metrics.arena_hbm_watermark.value() == total
    assert metrics.arena_hbm_bytes.value({"slab": "node_idle"}) == 8 * 4 * 4
    # a second identical view reuses the buffers: watermark is stable
    arena.device_view(arrays)
    assert arena.hbm_watermark_bytes == total
    arena.clear()
    assert arena.hbm_bytes_by_slab() == {}
    assert arena.hbm_watermark_bytes == 0
