"""TaskInfo/JobInfo bookkeeping invariants
(reference pkg/scheduler/api/job_info_test.go)."""

import pytest

from kube_batch_tpu.api import JobInfo, Resource, TaskStatus
from kube_batch_tpu.api.job_info import get_job_id
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.testing import build_pod, build_resource_list, build_task


def rl(cpu, mem):
    return build_resource_list(cpu, mem)


class TestTaskInfo:
    def test_new_task_from_pending_pod(self):
        t = build_task(name="p1", req=rl("1", "1G"))
        assert t.status == TaskStatus.PENDING
        assert t.resreq == Resource.from_resource_list(rl("1", "1G"))
        assert t.priority == 1  # default (job_info.go:80)

    def test_status_from_phase_and_node(self):
        assert build_task(phase=PodPhase.RUNNING, node_name="n1").status == TaskStatus.RUNNING
        assert build_task(phase=PodPhase.PENDING, node_name="n1").status == TaskStatus.BOUND
        assert build_task(phase=PodPhase.SUCCEEDED).status == TaskStatus.SUCCEEDED
        assert build_task(phase=PodPhase.FAILED).status == TaskStatus.FAILED

    def test_releasing_when_deleting(self):
        pod = build_pod(name="doomed", phase=PodPhase.RUNNING, node_name="n1")
        pod.metadata.deletion_timestamp = 123.0
        from kube_batch_tpu.api.job_info import TaskInfo

        assert TaskInfo(pod).status == TaskStatus.RELEASING

    def test_job_id_from_annotation(self):
        pod = build_pod(namespace="ns", name="p", group_name="pg1")
        assert get_job_id(pod) == "ns/pg1"
        assert get_job_id(build_pod(name="orphan")) == ""

    def test_clone_isolates_resources(self):
        t = build_task(req=rl("1", "1G"))
        c = t.clone()
        c.resreq.add(Resource(milli_cpu=1))
        assert t.resreq != c.resreq


class TestJobInfo:
    def test_add_task_updates_aggregates(self):
        """reference job_info_test.go TestAddTaskInfo."""
        job = JobInfo("ns/j1")
        t1 = build_task(name="p1", req=rl("1", "1G"), group_name="j1")
        t2 = build_task(name="p2", req=rl("2", "2G"), group_name="j1", node_name="n1",
                        phase=PodPhase.RUNNING)
        job.add_task_info(t1)
        job.add_task_info(t2)

        assert len(job.tasks) == 2
        assert job.total_request == Resource.from_resource_list(rl("3", "3G"))
        # only the running task is allocated
        assert job.allocated == Resource.from_resource_list(rl("2", "2G"))
        assert set(job.task_status_index) == {TaskStatus.PENDING, TaskStatus.RUNNING}

    def test_delete_task_restores_aggregates(self):
        """reference job_info_test.go TestDeleteTaskInfo."""
        job = JobInfo("ns/j1")
        t1 = build_task(name="p1", req=rl("1", "1G"))
        t2 = build_task(name="p2", req=rl("2", "2G"), node_name="n1", phase=PodPhase.RUNNING)
        job.add_task_info(t1)
        job.add_task_info(t2)
        job.delete_task_info(t2)

        assert len(job.tasks) == 1
        assert job.total_request == Resource.from_resource_list(rl("1", "1G"))
        assert job.allocated.is_empty()
        assert TaskStatus.RUNNING not in job.task_status_index

    def test_delete_missing_raises(self):
        job = JobInfo("ns/j1")
        with pytest.raises(KeyError):
            job.delete_task_info(build_task(name="ghost"))

    def test_update_task_status_moves_index(self):
        job = JobInfo("ns/j1")
        t = build_task(name="p1", req=rl("1", "1G"))
        job.add_task_info(t)
        job.update_task_status(t, TaskStatus.ALLOCATED)
        assert TaskStatus.PENDING not in job.task_status_index
        assert t.uid in job.task_status_index[TaskStatus.ALLOCATED]
        assert job.allocated == Resource.from_resource_list(rl("1", "1G"))

    def test_gang_predicates(self):
        job = JobInfo("ns/j1")
        job.min_available = 2
        t1 = build_task(name="p1", req=rl("1", "1G"))
        t2 = build_task(name="p2", req=rl("1", "1G"))
        job.add_task_info(t1)
        job.add_task_info(t2)

        assert job.valid_task_num() == 2
        assert job.ready_task_num() == 0
        assert not job.ready()

        job.update_task_status(t1, TaskStatus.ALLOCATED)
        assert job.ready_task_num() == 1
        assert not job.ready()
        job.update_task_status(t2, TaskStatus.PIPELINED)
        assert job.waiting_task_num() == 1
        assert job.pipelined()  # ready + waiting >= min
        assert not job.ready()

        job.update_task_status(t2, TaskStatus.BOUND)
        assert job.ready()

    def test_fit_error_histogram(self):
        job = JobInfo("ns/j1")
        job.nodes_fit_delta = {
            "n1": Resource(milli_cpu=-10),
            "n2": Resource(milli_cpu=-10, memory=-1),
        }
        msg = job.fit_error()
        assert "0/2 nodes are available" in msg
        assert "2 insufficient cpu" in msg
        assert "1 insufficient memory" in msg
        assert JobInfo("ns/empty").fit_error() == "0 nodes are available"

    def test_clone(self):
        job = JobInfo("ns/j1")
        job.min_available = 2
        job.queue = "q1"
        job.add_task_info(build_task(name="p1", req=rl("1", "1G")))
        c = job.clone()
        assert c.uid == job.uid and c.queue == "q1" and c.min_available == 2
        assert len(c.tasks) == 1
        # mutating the clone must not affect the original
        c.update_task_status(next(iter(c.tasks.values())), TaskStatus.ALLOCATED)
        assert job.ready_task_num() == 0
