"""Node-class compressed solve (ISSUE 20): the node axis folded into
equivalence classes, feasibility+score+argmax at class granularity,
concrete placement replayed through the serial tiebreak.

The invariants pinned here:

- **parity**: compressed ≡ uncompressed ≡ serial, bind for bind, on the
  heterogeneous-pool world — single chip, every mesh size, the
  pod-affinity pause/resume hybrid, and a streaming micro-cycle over a
  resident table that absorbed a peer shard's occupancy patch;
- **dynamics**: in-solve splits (a bound node leaves its class), the
  segment iteration cap forcing a mid-solve re-pack, and re-merges of
  bound-alike nodes all demonstrably fire, with the power-of-two slot
  bucket sticky across cycles;
- **degrade, never drop**: the ``solve.class_table`` fault point drops
  the cycle to the uncompressed tier with identical binds and a metered
  degrade;
- **zero warm recompiles**: 1%-churn sessions (the bench churn row at
  test scale) run under a CompileSentinel budget of zero;
- **tooling**: the wide-key native ``class_dedup`` agrees with the
  np.unique fallback, the class explain path is byte-identical to the
  per-node one, and ``hack/bench_diff.py`` gates ``compression_ratio``
  and the parity bit while keeping the solve-cost split informational.
"""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu import faults, metrics
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.ops import class_solve
from kube_batch_tpu.ops.class_solve import ENV, _smoke_world, dedup_rows
from kube_batch_tpu.testing import FakeCache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The reference's default conf: drf + proportion fold into the loop
# state, so the class key carries the fairness planes too.
TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

N_SMOKE_NODES = 4 * 18


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def run_xla(cluster, compress, mesh=None, action=None, tiers=TIERS_YAML):
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    saved = os.environ.get(ENV)
    os.environ[ENV] = "1" if compress else "0"
    try:
        action = action or XlaAllocateAction()
        args = {"xla_allocate": {"mesh": mesh}} if mesh else {}
        cache = FakeCache(cluster)
        ssn = open_session(cache, parse_scheduler_conf(tiers).tiers, args)
        try:
            action.execute(ssn)
        finally:
            close_session(ssn)
        return dict(cache.binder.binds), action
    finally:
        if saved is None:
            os.environ.pop(ENV, None)
        else:
            os.environ[ENV] = saved


def run_serial(cluster, tiers=TIERS_YAML):
    from kube_batch_tpu.actions.allocate import AllocateAction

    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(tiers).tiers)
    try:
        AllocateAction().execute(ssn)
    finally:
        close_session(ssn)
    return dict(cache.binder.binds)


@pytest.fixture(scope="module")
def smoke_sides():
    """Serial / uncompressed / compressed over the heterogeneous-pool
    world, computed once for the whole module."""
    serial = run_serial(_smoke_world())
    plain, _ = run_xla(_smoke_world(), compress=False)
    comp, action = run_xla(_smoke_world(), compress=True)
    return {
        "serial": serial,
        "plain": plain,
        "comp": comp,
        "tier": action.last_solver_tier,
        "stats": dict(action.last_class_stats or {}),
    }


# -- parity ------------------------------------------------------------------


def test_compressed_parity_vs_uncompressed_and_serial(smoke_sides):
    assert smoke_sides["comp"] == smoke_sides["plain"] == smoke_sides["serial"]
    assert len(smoke_sides["comp"]) > 0
    assert smoke_sides["tier"] == "class_xla"
    s = smoke_sides["stats"]
    assert 0 < s["class_count"] < N_SMOKE_NODES
    assert s["compression_ratio"] > 5


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_mesh_parity(smoke_sides, n_devices):
    """The class kernel under GSPMD: the slot axis is replicated, the
    member replay stays on host — every mesh size must reproduce the
    single-chip (mesh-off) binds exactly."""
    comp, action = run_xla(
        _smoke_world(), compress=True, mesh=f"cpu:{n_devices}"
    )
    assert action.last_mesh_size == n_devices, "sharded path did not engage"
    # blocked mesh rung by default, plain GSPMD when KBT_MESH_PALLAS=off
    assert action.last_solver_tier in ("class_mesh_pallas", "class_sharded_xla")
    assert comp == smoke_sides["plain"]


def _affinity_world():
    """test_parallel's pause/resume world with a duplicated node pool:
    4 byte-identical nodes (one diverges by carrying the anchor), two
    pod-affinity host-only tasks forcing two pause/resume trips through
    the segmented hybrid."""
    from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    anchor = build_pod(
        name="anchor",
        node_name="n0",
        phase=PodPhase.RUNNING,
        req=build_resource_list(cpu=1, memory="128Mi"),
        labels={"app": "db"},
    )
    pods, groups = [anchor], []
    for i in range(12):
        p = build_pod(
            name=f"p{i}",
            group_name=f"g{i}",
            req=build_resource_list(cpu=1, memory="256Mi"),
        )
        p.metadata.creation_timestamp = float(i)
        if i in (4, 9):
            p.affinity = Affinity(
                pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
            )
        pg = build_pod_group(f"g{i}", min_member=1)
        pg.metadata.creation_timestamp = float(i)
        pods.append(p)
        groups.append(pg)
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
        for i in range(4)
    ]
    return build_cluster(pods, nodes, groups, [build_queue("default")])


def test_pod_affinity_pause_resume_hybrid_parity():
    """Host-only tasks pause the class kernel too: the gathered state
    is serial-stepped and re-enters the compressed resume program, on
    and off a mesh, with binds identical to the uncompressed tiers."""
    plain, _ = run_xla(_affinity_world(), compress=False)
    comp, a1 = run_xla(_affinity_world(), compress=True)
    comp4, a4 = run_xla(_affinity_world(), compress=True, mesh="cpu:4")
    assert a1.last_solver_tier.startswith("class_")
    assert a4.last_mesh_size == 4
    assert comp == comp4 == plain and len(plain) == 12


# -- split / re-merge / segment-cap dynamics ---------------------------------


def test_split_remerge_segment_dynamics_across_cycles():
    # extra arrivals push the solve past one segment's iteration budget
    # (c_pad/2 = 64 here), forcing the in-solve re-pack
    comp, action = run_xla(_smoke_world(arrivals=12), compress=True)
    s1 = dict(action.last_class_stats)
    world2 = lambda: _smoke_world(bound=comp, arrivals=18)  # noqa: E731
    comp2, _ = run_xla(world2(), compress=True, action=action)
    s2 = dict(action.last_class_stats)
    plain2, _ = run_xla(world2(), compress=False)
    assert comp2 == plain2

    # a bound node's occupancy diverges from its class mid-solve
    assert s1["splits"] > 0
    # the segment iteration cap forces >=1 in-solve re-pack, where
    # bound-alike singletons collapse back into shared classes
    assert s1["segments"] >= 2
    assert s1["remerges"] + s2["remerges"] > 0
    # power-of-two slot bucket, sticky across cycles (never shrinks)
    for s in (s1, s2):
        assert s["c_pad"] & (s["c_pad"] - 1) == 0 and s["c_pad"] > 0
    assert s2["c_pad"] >= s1["c_pad"]
    assert 0 < s2["class_count"] <= s2["c_pad"]


# -- chaos: degrade, never drop ----------------------------------------------


def test_class_table_fault_degrades_loudly_with_parity(smoke_sides):
    """solve.class_table: a poisoned class table drops the cycle to the
    uncompressed tier — binds identical, degrade + injection metered."""
    labels = {"tier": "class_solve", "reason": "class_table"}
    before = metrics.degraded_cycles.value(labels)
    faults.registry.arm("solve.class_table", count=1)
    binds, action = run_xla(_smoke_world(), compress=True)
    assert binds == smoke_sides["plain"]
    assert action.last_solver_tier == "xla"  # fell to the wrapped rung
    assert action.last_class_stats is None
    assert metrics.degraded_cycles.value(labels) == before + 1
    assert metrics.fault_injections.value({"point": "solve.class_table"}) >= 1


# -- wide-key dedup: native vs fallback --------------------------------------


def test_dedup_rows_native_vs_fallback_partition_parity():
    """Multi-slab keys (f64 matrix + i32 + bool matrix + 1-D i64)
    through the native multi-buffer hash and the np.unique fallback
    (forced by the native.class_dedup fault): class ORDER differs by
    contract, the partition into classes must not."""
    from kube_batch_tpu.native import lib as native

    assert native is not None and hasattr(native, "class_dedup")

    rng = np.random.default_rng(0)
    n = 4096
    slabs = [
        (rng.integers(0, 3, (n, 5)) * 0.5).astype(np.float64),
        rng.integers(0, 4, n).astype(np.int32),
        rng.integers(0, 2, (n, 3)).astype(bool),
        rng.integers(0, 2, n).astype(np.int64),
    ]
    first_n, inv_n = dedup_rows(slabs)
    faults.registry.arm("native.class_dedup")
    first_f, inv_f = dedup_rows(slabs)
    _, _, fired = faults.registry.active()["native.class_dedup"]
    assert fired >= 1, "fallback path never engaged"

    def partition(first, inv):
        groups: dict[int, list[int]] = {}
        for row, cls in enumerate(inv):
            groups.setdefault(int(cls), []).append(row)
        for cls, members in groups.items():
            # the representative is a member of its own class
            assert int(first[cls]) in members
        return sorted(tuple(m) for m in groups.values())

    assert partition(first_n, inv_n) == partition(first_f, inv_f)
    assert len(first_n) == len(first_f) < n
    assert first_n.dtype == np.int64 and inv_n.dtype == np.int32


# -- class-granularity explain -----------------------------------------------


def test_explain_class_path_byte_identical():
    """explain_batch_classes must reproduce explain_batch exactly —
    eliminations, feasible counts, would-fit bits and the top-k
    near-miss list (same argmax tie contract) — from one evaluated row
    per class."""
    from kube_batch_tpu.ops import explain as ops_explain
    from kube_batch_tpu.ops.encode import encode_session
    from kube_batch_tpu.ops.kernels import solve_allocate_state

    ssn = open_session(
        FakeCache(_smoke_world()), parse_scheduler_conf(TIERS_YAML).tiers
    )
    enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64)
    close_session(ssn, discard=True)
    arrays = dict(enc.arrays)
    arrays.update(
        w_least=np.float64(1), w_balanced=np.float64(1),
        w_aff=np.float64(1), w_podaff=np.float64(1),
    )
    state = solve_allocate_state(arrays)

    rep_rows = ops_explain.pad_rows(
        [int(arrays["job_start"][j])
         for j in range(len(enc.jobs)) if arrays["job_valid"][j]]
    )
    st = tuple(
        np.asarray(getattr(state, f))
        for f in ("idle", "rel", "used", "ntasks", "nports")
    )
    base = ops_explain.explain_batch(arrays, *st, rep_rows)
    comp = ops_explain.explain_batch_classes(arrays, *st, rep_rows)
    real = np.asarray(rep_rows) >= 0
    assert real.sum() > 0
    for b, c in zip(base, comp):
        np.testing.assert_array_equal(np.asarray(b)[real], np.asarray(c)[real])


# -- streaming micro-cycle over an absorbed peer patch -----------------------


def test_streaming_micro_cycle_absorb_patch_class_parity():
    """Federated streaming shape: a full cycle adopts the resident node
    table, a peer shard's bind lands as an absorb-mode occupancy patch
    (not a degrade), and the next micro-cycle solves fresh arrivals over
    the patched residents — compressed vs uncompressed must agree bind
    for bind on both the full cycle and the micro-cycle."""
    from kube_batch_tpu.apis.types import PodPhase
    from kube_batch_tpu.streaming import StreamState, open_micro_session
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    def arrival_jobs():
        pods, pgs = [], []
        for g in range(2):
            name = f"arrival-{g}"
            pgs.append(build_pod_group(name, min_member=2))
            for m in range(2):
                pods.append(
                    build_pod(
                        name=f"{name}-t{m}", group_name=name,
                        req=build_resource_list(cpu="1", memory="2Gi"),
                    )
                )
        scratch = build_cluster(
            pods, [build_node("scratch", build_resource_list(cpu=1))],
            pgs, [build_queue("default")],
        )
        ssn = open_session(
            FakeCache(scratch), parse_scheduler_conf(TIERS_YAML).tiers
        )
        jobs, queues = dict(ssn.jobs), dict(ssn.queues)
        close_session(ssn, discard=True)
        return jobs, queues

    def side(compress):
        from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

        saved = os.environ.get(ENV)
        os.environ[ENV] = "1" if compress else "0"
        try:
            action = XlaAllocateAction()
            tiers = parse_scheduler_conf(TIERS_YAML).tiers
            cache = FakeCache(_smoke_world())
            ssn = open_session(cache, tiers)
            action.execute(ssn)
            st = StreamState()
            st.adopt_full_cycle(ssn)
            close_session(ssn)
            full = dict(cache.binder.binds)

            # peer shard binds a pod that fills large-000 down to
            # 500m/1Gi — absorbed as an occupancy patch (table stays
            # valid), and consequential: the 1cpu/2Gi arrivals can no
            # longer land there
            idle = st.nodes["large-000"].idle
            peer = build_pod(
                name="peer-0", node_name="large-000",
                phase=PodPhase.RUNNING,
                req=build_resource_list(
                    cpu=f"{int(idle.milli_cpu) - 500}m",
                    memory=f"{int(idle.memory // 2**20) - 1024}Mi",
                ),
            )
            assert st.apply_bound_patches([("add", "default/peer-0", peer)])
            assert st.valid

            jobs, queues = arrival_jobs()
            mssn = open_micro_session(cache, tiers, {}, jobs, st.nodes, queues)
            mssn.micro_cycle = True
            action.execute(mssn)
            close_session(mssn)
            micro = {
                k: v for k, v in cache.binder.binds.items() if k not in full
            }
            return full, micro, action.last_solver_tier
        finally:
            if saved is None:
                os.environ.pop(ENV, None)
            else:
                os.environ[ENV] = saved

    full_c, micro_c, tier = side(True)
    full_p, micro_p, _ = side(False)
    assert tier.startswith("class_"), "micro-cycle did not solve at class level"
    assert full_c == full_p
    assert micro_c == micro_p and len(micro_c) == 4
    # the absorbed peer occupancy was consequential: nothing else fits
    # on large-000 after a 28-cpu resident landed there
    assert "large-000" not in micro_c.values()


# -- zero warm recompiles under churn ----------------------------------------


def test_warm_churn_sessions_zero_recompiles():
    """The bench churn row at test scale: 1%-class node churn (the
    resident shape moves with the salt) must re-key classes without
    moving the power-of-two class bucket — warm sessions compile
    nothing."""
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction
    from kube_batch_tpu.analysis.trace.sentinel import CompileSentinel
    from kube_batch_tpu.models import uniform_pool

    action = XlaAllocateAction()
    world = lambda salt: uniform_pool(  # noqa: E731
        800, 100, churn=0.02, churn_salt=salt
    )
    for salt in (0, 1):  # compile + warm the sticky bucket
        run_xla(world(salt), compress=True, action=action)
    with CompileSentinel("class solve warm churn", budget=0) as cs:
        for salt in (2, 3):
            _, action = run_xla(world(salt), compress=True, action=action)
    assert cs.compiles == 0
    assert action.last_solver_tier.startswith("class_")
    assert action.last_class_stats["compression_ratio"] > 10


# -- bench_diff: class columns -----------------------------------------------


def _bench_diff_mod():
    spec = importlib.util.spec_from_file_location(
        "kbt_hack_bench_diff_class", os.path.join(REPO, "hack", "bench_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_diff_compression_ratio_shrink_is_regression():
    bd = _bench_diff_mod()
    old = {"uniform_pool_400k_40k_classes": {
        "p50_s": 10.0, "compression_ratio": 4000.0,
    }}
    new = {"uniform_pool_400k_40k_classes": {
        "p50_s": 10.0, "compression_ratio": 90.0,
    }}
    summary = bd.diff_rows(old, new, threshold=0.15)
    assert summary["ok"] is False
    assert [f["kind"] for f in summary["findings"]] == ["regression"]
    assert "compression_ratio" in summary["findings"][0]["msg"]
    # the reverse direction is an improvement, not a finding
    summary = bd.diff_rows(new, old, threshold=0.15)
    assert summary["ok"] is True
    assert any("compression_ratio" in l for l in summary["improvements"])


def test_bench_diff_parity_bit_flip_is_fatal():
    bd = _bench_diff_mod()
    old = {"row": {"p50_s": 1.0, "placements_equal_uncompressed": True}}
    new = {"row": {"p50_s": 0.5, "placements_equal_uncompressed": False}}
    summary = bd.diff_rows(old, new, threshold=0.15)
    assert summary["ok"] is False
    assert [f["kind"] for f in summary["findings"]] == ["parity"]


def test_bench_diff_class_split_columns_are_info_only():
    """The solve-cost split (where the time went) must never flag, and
    must never mask a real p50 regression either."""
    bd = _bench_diff_mod()
    old = {"row": {
        "p50_s": 10.0, "class_count": 18, "class_group_s": 0.4,
        "class_kernel_s": 8.0, "class_segments": 196,
        "class_solve_speedup_vs_uncompressed": 5.4,
    }}
    benign = {"row": {
        "p50_s": 10.1, "class_count": 1400, "class_group_s": 1.4,
        "class_kernel_s": 9.0, "class_segments": 400,
        "class_solve_speedup_vs_uncompressed": 2.0,
    }}
    summary = bd.diff_rows(old, benign, threshold=0.15)
    assert summary["ok"] is True and summary["findings"] == []
    assert any("class_count 18 -> 1400" in l for l in summary["info"])

    regressed = dict(benign["row"], p50_s=20.0)
    summary = bd.diff_rows(old, {"row": regressed}, threshold=0.15)
    assert summary["ok"] is False
    assert [f["kind"] for f in summary["findings"]] == ["regression"]
    assert "p50_s" in summary["findings"][0]["msg"]
