"""Pallas fused solve ≡ XLA while-loop solve.

The XLA kernel (ops/kernels.py) is itself pinned against the serial
oracle (tests/test_xla_allocate.py); these tests pin the fused Pallas
kernel (ops/pallas_solve.py) against the XLA kernel, decision for
decision, on the same float32 snapshots. On CPU the Pallas kernel runs
in interpreter mode; on the real chip the compiled kernel is covered by
bench.py's serial-vs-xla bind assertions (the action auto-selects the
Pallas path on TPU).
"""

import numpy as np
import pytest

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.models import multi_tenant_ml, synthetic
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.ops.kernels import solve_allocate_state
from kube_batch_tpu.ops.pallas_solve import PallasSolver, supported
from kube_batch_tpu.testing import FakeCache

from test_xla_allocate import DEFAULT_TIERS_YAML, gen_cluster


def solve_both(cluster, drf=True, proportion=True):
    """Encode once (float32), run the XLA and interpret-mode Pallas
    solvers on identical arrays; return both final states."""
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    enc = encode_session(
        ssn.jobs,
        ssn.nodes,
        ssn.queues,
        dtype=np.float32,
        drf=ssn.plugins.get("drf") if drf else None,
        proportion=ssn.plugins.get("proportion") if proportion else None,
    )
    close_session(ssn)
    if not enc.tasks:
        return None, None
    a = dict(enc.arrays)
    a["w_least"] = np.float32(1)
    a["w_balanced"] = np.float32(1)
    a["w_aff"] = np.float32(1)
    a["w_podaff"] = np.float32(1)
    assert supported(a)
    lax_state = solve_allocate_state(a, None, enable_drf=drf, enable_proportion=proportion)
    pallas_state = PallasSolver(a, drf, proportion, interpret=True, fetch_f32=True).solve(None)
    return lax_state, pallas_state


def assert_states_equal(lax_state, pallas_state, ctx=""):
    l, p = lax_state, pallas_state
    assert int(l.step) == int(p.step), f"{ctx}: step"
    np.testing.assert_array_equal(np.asarray(l.assigned_node), p.assigned_node, err_msg=f"{ctx}: node")
    np.testing.assert_array_equal(np.asarray(l.assigned_kind), p.assigned_kind, err_msg=f"{ctx}: kind")
    np.testing.assert_array_equal(np.asarray(l.assign_pos), p.assign_pos, err_msg=f"{ctx}: pos")
    np.testing.assert_array_equal(np.asarray(l.ready_cnt), p.ready_cnt, err_msg=f"{ctx}: ready")
    np.testing.assert_array_equal(np.asarray(l.ptr), p.ptr, err_msg=f"{ctx}: ptr")
    np.testing.assert_array_equal(np.asarray(l.job_active), p.job_active, err_msg=f"{ctx}: active")
    np.testing.assert_array_equal(np.asarray(l.q_dropped), p.q_dropped, err_msg=f"{ctx}: q_dropped")
    np.testing.assert_allclose(np.asarray(l.idle), p.idle, err_msg=f"{ctx}: idle")
    np.testing.assert_allclose(np.asarray(l.used), p.used, err_msg=f"{ctx}: used")
    np.testing.assert_allclose(np.asarray(l.job_alloc), p.job_alloc, err_msg=f"{ctx}: job_alloc")
    np.testing.assert_allclose(np.asarray(l.q_alloc), p.q_alloc, err_msg=f"{ctx}: q_alloc")


def test_synthetic_small():
    assert_states_equal(*solve_both(synthetic(40, 5)))


def test_synthetic_medium():
    assert_states_equal(*solve_both(synthetic(200, 20)))


def test_scalar_resources_multi_tenant():
    """GPU/TPU scalar slots exercise the has-scalar gates and the Go
    nil-scalar-map parity bits inside the kernel."""
    assert_states_equal(
        *solve_both(multi_tenant_ml(n_jobs=8, n_nodes=8, n_queues=3))
    )


def test_no_drf_no_proportion_variant():
    lax_state, pallas_state = solve_both(synthetic(60, 6), drf=False, proportion=False)
    assert_states_equal(lax_state, pallas_state)


@pytest.mark.parametrize("batch", range(3))
def test_property_pallas_equals_xla(batch):
    """Random snapshots (gang jobs, priorities, selectors, taints,
    residents, multi-queue) — the fused kernel must match the XLA kernel
    decision for decision under the default conf."""
    for seed in range(batch * 4, (batch + 1) * 4):
        lax_state, pallas_state = solve_both(gen_cluster(seed))
        if lax_state is None:
            continue
        assert_states_equal(lax_state, pallas_state, ctx=f"seed {seed}")


def test_action_uses_pallas_in_interpret_mode(monkeypatch):
    """End-to-end through the action: KBT_PALLAS=interpret must produce
    the exact lax-path session outcome (binds and task states)."""
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    def run(mode):
        monkeypatch.setenv("KBT_PALLAS", mode)
        cache = FakeCache(synthetic(80, 8))
        ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
        XlaAllocateAction(dtype=np.float32).execute(ssn)
        state = {}
        for job in ssn.jobs.values():
            for tasks in job.task_status_index.values():
                for t in tasks.values():
                    state[t.uid] = (t.status, t.node_name)
        close_session(ssn)
        return state, dict(cache.binder.binds)

    lax_state, lax_binds = run("0")
    pallas_state, pallas_binds = run("interpret")
    assert pallas_state == lax_state
    assert pallas_binds == lax_binds


def test_fold_boundary_exact_128_tasks():
    """T exactly at the fold boundary (128 tasks -> one full row)."""
    assert_states_equal(*solve_both(synthetic(128, 4, tasks_per_job=8)))


def test_fold_boundary_129_tasks():
    """T one past the fold boundary (129 -> two rows, second nearly empty).
    synthetic() builds n_pods//tasks_per_job jobs; 129 with 3-task jobs
    gives 43 jobs x 3 = 129 tasks exactly."""
    assert_states_equal(*solve_both(synthetic(129, 5, tasks_per_job=3)))


def test_single_node_single_job():
    assert_states_equal(*solve_both(synthetic(6, 1, tasks_per_job=6)))


def test_more_tasks_than_capacity():
    """Oversubscribed: most tasks must stay pending, gang barrier holds."""
    lax_state, pallas_state = solve_both(synthetic(300, 2, tasks_per_job=10))
    assert_states_equal(lax_state, pallas_state)
    assert int(pallas_state.step) < 300


def test_supported_envelope_edges():
    """Out-of-envelope snapshots must be detected so the action routes to
    the XLA kernel instead of failing in Mosaic."""
    import numpy as np

    from kube_batch_tpu.ops import pallas_solve

    def base(T=64, N=16, R=2, P=1, GT=1):
        return {
            "task_req": np.zeros((T, R), np.float32),
            "task_res": np.zeros((T, R), np.float32),
            "task_gid": np.zeros(T, np.int32),
            "task_has_sc": np.zeros(T, bool),
            "task_res_has_sc": np.zeros(T, bool),
            "task_host_only": np.zeros(T, bool),
            "task_ports": np.zeros((T, P), bool),
            "compat": np.zeros((GT, 4), bool),
            "node_idle": np.zeros((N, R), np.float32),
            "job_min": np.zeros(8, np.int32),
            "queue_rank": np.zeros(2, np.int32),
        }

    assert pallas_solve.supported(base())
    assert not pallas_solve.supported(base(R=9))  # resource rank beyond R8
    assert not pallas_solve.supported(base(P=40))  # > 31 distinct host ports
    # compat expansion past the VMEM budget (GT x N too large)
    assert not pallas_solve.supported(base(GT=4096, N=8192))


def test_vmem_budget_is_device_aware(monkeypatch):
    """v5e-class cores (128 MiB VMEM) get the wide budget — measured on
    the bench chip: 400k x 40k (~33 MiB resident) compiles and runs —
    while unknown cores keep the conservative default, and
    KBT_VMEM_BUDGET overrides both."""
    import jax

    from kube_batch_tpu.ops import pallas_solve

    class Dev:
        def __init__(self, kind):
            self.device_kind = kind

    monkeypatch.setattr(jax, "devices", lambda *a: [Dev("TPU v5 lite")])
    assert pallas_solve.vmem_budget() == 96 * 1024 * 1024
    monkeypatch.setattr(jax, "devices", lambda *a: [Dev("TPU v3")])
    assert pallas_solve.vmem_budget() == pallas_solve._DEFAULT_VMEM_BUDGET
    monkeypatch.setenv("KBT_VMEM_BUDGET", str(7 * 1024 * 1024))
    assert pallas_solve.vmem_budget() == 7 * 1024 * 1024


def test_many_scalar_resources_falls_back_to_lax(monkeypatch):
    """A cluster with 7+ distinct scalar resources (R > 8) runs the XLA
    kernel via the action and still matches serial."""
    import numpy as np

    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    scalars = {f"vendor{i}.com/dev": 2 for i in range(7)}

    def mk():
        pods = [
            build_pod(
                name=f"p{i}",
                group_name="pg",
                req=build_resource_list(cpu=1, memory="1Gi", **scalars),
            )
            for i in range(3)
        ]
        nodes = [
            build_node(
                f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=10, **scalars)
            )
            for i in range(2)
        ]
        return build_cluster(
            pods, nodes, [build_pod_group("pg", min_member=1)], [build_queue("default")]
        )

    monkeypatch.setenv("KBT_PALLAS", "interpret")  # would use pallas if eligible

    def run(action):
        cache = FakeCache(mk())
        ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
        if action == "serial":
            from kube_batch_tpu.actions.allocate import AllocateAction

            AllocateAction().execute(ssn)
        else:
            XlaAllocateAction(dtype=np.float32).execute(ssn)
        binds = dict(cache.binder.binds)
        close_session(ssn)
        return binds

    assert run("xla") == run("serial") != {}


def test_pod_affinity_keeps_pallas_kernel(monkeypatch):
    """VERDICT r3 item 7: live InterPodAffinity no longer forces the XLA
    kernel. A cluster with affinity pods (two host-stepped pauses) runs
    the Pallas solver across every segment — its affinity static
    re-folded per resume — and matches the serial action exactly."""
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction
    from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
    from kube_batch_tpu.ops import pallas_solve
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    def mk():
        pods, groups = [], []
        for i in (0, 1):
            pods.append(
                build_pod(
                    name=f"anchor{i}",
                    node_name=f"n{i}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="128Mi"),
                    labels={"app": "db"},
                )
            )

        def gang(name, pod, ts):
            pod.metadata.creation_timestamp = ts
            pg = build_pod_group(name, min_member=1)
            pg.metadata.creation_timestamp = ts
            pods.append(pod)
            groups.append(pg)

        for i, ts in ((0, 0.0), (1, 10.0)):
            aff = build_pod(
                name=f"aff{i}", group_name=f"g-aff{i}",
                req=build_resource_list(cpu=1, memory="256Mi"),
            )
            aff.affinity = Affinity(
                pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
            )
            gang(f"g-aff{i}", aff, ts)
        for i in range(6):
            gang(
                f"g-fill{i}",
                build_pod(
                    name=f"fill{i}", group_name=f"g-fill{i}",
                    req=build_resource_list(cpu=2, memory="2Gi"),
                ),
                1.0 + i,
            )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
            for i in range(3)
        ]
        return build_cluster(pods, nodes, groups, [build_queue("default")])

    monkeypatch.setenv("KBT_PALLAS", "interpret")
    solve_calls = {"n": 0}
    orig_solve = pallas_solve.PallasSolver.solve

    def counting_solve(self, state=None):
        solve_calls["n"] += 1
        return orig_solve(self, state)

    monkeypatch.setattr(pallas_solve.PallasSolver, "solve", counting_solve)

    def run(action):
        cache = FakeCache(mk())
        ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
        if action == "serial":
            from kube_batch_tpu.actions.allocate import AllocateAction

            AllocateAction().execute(ssn)
        else:
            XlaAllocateAction(dtype=np.float32).execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds)

    serial_binds = run("serial")
    xla_binds = run("xla")
    assert xla_binds == serial_binds
    assert len(serial_binds) == 8
    # initial segment + a resume per host-stepped affinity pod
    assert solve_calls["n"] >= 3, f"pallas did not drive the hybrid ({solve_calls})"


class TestClassDedupParity:
    """ADVICE r5 (low): the native class_dedup numbers classes in
    first-occurrence order, the np.unique fallback in sorted-key order.
    Class id order is documented as meaningless — these tests pin that
    the two paths produce the SAME task partition and the SAME binds, so
    a future consumer tie-breaking on class id cannot diverge undetected
    between KBT_NATIVE=0 and native runs."""

    def _arrays(self):
        """A snapshot with real class structure: duplicate pods (one
        class), a distinct-resource pod, and port/gang variation."""
        cache = FakeCache(synthetic(96, 8, tasks_per_job=6))
        ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
        enc = encode_session(
            ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float32,
            drf=ssn.plugins.get("drf"), proportion=ssn.plugins.get("proportion"),
        )
        close_session(ssn)
        return dict(enc.arrays)

    def test_partition_and_reconstruction_parity(self):
        from kube_batch_tpu import faults
        from kube_batch_tpu.native import lib as native_lib
        from kube_batch_tpu.ops import pallas_solve as PS

        if native_lib is None or not hasattr(native_lib, "class_dedup"):
            pytest.skip("native class_dedup unavailable in this image")
        a = self._arrays()

        PS._class_inv_slot = None  # drop the per-cycle memo
        tports_n, first_n, inv_n = PS._class_inverse(a)

        faults.registry.arm("native.class_dedup")  # force the fallback
        try:
            PS._class_inv_slot = None
            tports_f, first_f, inv_f = PS._class_inverse(a)
        finally:
            faults.registry.reset()
            PS._class_inv_slot = None

        assert np.array_equal(tports_n, tports_f)
        assert first_n.shape == first_f.shape  # same class count
        # each representative index reconstructs its own class id
        assert np.array_equal(inv_n[first_n], np.arange(first_n.shape[0]))
        assert np.array_equal(inv_f[first_f], np.arange(first_f.shape[0]))

        # the task partition (which tasks share a class) is identical,
        # independent of class numbering
        def partition(inv):
            groups: dict[int, list[int]] = {}
            for task_row, cls in enumerate(inv.tolist()):
                groups.setdefault(cls, []).append(task_row)
            return sorted(tuple(g) for g in groups.values())

        assert partition(inv_n) == partition(inv_f)

    def test_binds_identical_native_vs_fallback(self, monkeypatch):
        """Same snapshot through the full action (interpret-mode pallas,
        which consumes the class tables) with and without the native
        dedup: identical binds."""
        from kube_batch_tpu import faults
        from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction
        from kube_batch_tpu.ops import pallas_solve as PS

        monkeypatch.setenv("KBT_PALLAS", "interpret")

        def run():
            PS._class_inv_slot = None
            cache = FakeCache(synthetic(80, 8))
            ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
            XlaAllocateAction(dtype=np.float32).execute(ssn)
            close_session(ssn)
            return dict(cache.binder.binds)

        native_binds = run()
        faults.registry.arm("native.class_dedup")
        try:
            fallback_binds = run()
        finally:
            faults.registry.reset()
            PS._class_inv_slot = None
        assert native_binds == fallback_binds != {}
