"""Multi-chip sharding tests on the 8-device virtual CPU mesh
(conftest forces xla_force_host_platform_device_count=8): the sharded
solve must agree exactly with the single-device solve, and the driver's
dryrun contract must hold."""

import os

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import open_session
from kube_batch_tpu.models import multi_queue, synthetic
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.ops.kernels import solve_allocate
from kube_batch_tpu.parallel import make_mesh, sharded_solve_allocate
from kube_batch_tpu.testing import FakeCache

TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""


def encoded(cluster):
    ssn = open_session(FakeCache(cluster), parse_scheduler_conf(TIERS_YAML).tiers)
    enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64)
    arrays = dict(enc.arrays)
    arrays.update(w_least=np.float64(1), w_balanced=np.float64(1), w_aff=np.float64(1), w_podaff=np.float64(1))
    return enc, arrays


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_solve_matches_single_device(n_devices):
    enc, arrays = encoded(synthetic(120, 24, seed=3))
    single = solve_allocate(arrays)
    mesh = make_mesh(n_devices)
    sharded = sharded_solve_allocate(arrays, mesh)
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_kind), np.asarray(sharded.assigned_kind)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assign_pos), np.asarray(sharded.assign_pos)
    )
    assert int(single.n_assigned) == int(sharded.n_assigned) > 0


def test_sharded_solve_multi_queue():
    enc, arrays = encoded(multi_queue(96, 16, n_queues=3, tasks_per_job=6, seed=7))
    single = solve_allocate(arrays)
    sharded = sharded_solve_allocate(arrays, make_mesh(8))
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )


def test_dryrun_multichip_contract():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_hermetic_to_poisoned_tpu():
    """VERDICT r4 item 1: a wedged/unavailable TPU backend must not be
    able to fail the virtual-CPU-mesh correctness check. Run the driver
    contract (`__graft_entry__.py dryrun 8`) in a subprocess where the
    ambient accelerator genuinely cannot initialize: the axon plugin is
    never registered (its sitecustomize is gated on PALLAS_AXON_POOL_IPS)
    and libtpu discovery points at a nonexistent library — so with
    JAX_PLATFORMS naming a non-cpu backend, any unpinned backend lookup
    raises instead of silently falling back."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon backend now unregistered
    env["TPU_LIBRARY_PATH"] = "/nonexistent/libtpu.so"
    env["JAX_PLATFORMS"] = "axon"  # unknown backend unless the dryrun pins cpu
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "__graft_entry__.py"), "dryrun", "8"],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "dryrun_multichip ok" in out.stdout


def test_dryrun_inprocess_path_touches_only_cpu():
    """The in-process dryrun path (taken when the process is already
    pinned to cpu, as the test/driver conftest does): replace every
    non-cpu backend factory with a raising stub, so if ANY eager or
    jitted op dispatches outside cpu, init fails loudly — a hard
    guarantee independent of plugin internals."""
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent(
        """
        import os
        import sys
        sys.path.insert(0, %r)
        # Before any backend init: the forced device count must land on
        # the cpu client the poisoned run will use.
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        import jax
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        # Force lazy PJRT plugin discovery NOW (initializes only cpu,
        # registers every entry-point plugin's factory) so the poison
        # below also covers lazily-registered plugins.
        xb.backends()

        def _boom(*a, **k):
            raise RuntimeError("poisoned: non-cpu backend initialized")

        for name in list(xb._backend_factories):
            if name != "cpu":
                reg = xb._backend_factories[name]
                try:
                    poisoned = reg._replace(factory=_boom, fail_quietly=False)
                except AttributeError:
                    import dataclasses
                    poisoned = dataclasses.replace(
                        reg, factory=_boom, fail_quietly=False)
                xb._backend_factories[name] = poisoned

        import __graft_entry__ as ge
        ge.dryrun_multichip(8)
        """
        % _REPO
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    # Forbid the subprocess fallback inside the scripted process: if the
    # in-process hermetic gate regresses, dryrun must raise, not re-exec
    # an unpoisoned child that would turn this test vacuously green.
    env["KBT_DRYRUN_CHILD"] = "1"
    out = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
        cwd=_REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "dryrun_multichip ok" in out.stdout


def test_entry_contract():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert int(out.n_assigned) > 0


DEFAULT_TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def test_xla_allocate_action_sharded_10k_parity():
    """VERDICT r3 item 1 done-criterion: the multi-chip path through the
    REAL action — conf-style actionArguments select an 8-device mesh, the
    action is fetched from the L4 registry, and at 10k tasks x 1k nodes
    the sharded run's binds equal the single-chip run's exactly."""
    from kube_batch_tpu.framework import close_session, get_action

    def run(mesh_spec):
        cache = FakeCache(multi_queue(10_000, 1000))
        ssn = open_session(
            cache,
            parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers,
            {"xla_allocate": {"mesh": mesh_spec}},
        )
        action = get_action("xla_allocate")
        action.execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds), action.last_mesh_size

    sharded, mesh_n = run("cpu:8")
    assert mesh_n == 8, "sharded path did not engage"
    single, mesh_1 = run("off")
    assert mesh_1 == 1
    assert len(sharded) == 10_000
    assert sharded == single


def test_scheduler_conf_mesh_reaches_action():
    """The actionArguments flow: conf text -> Scheduler -> open_session ->
    xla_allocate resolves the mesh (2-device virtual CPU)."""
    from kube_batch_tpu.framework import close_session, get_action

    action_args = parse_scheduler_conf(
        'actionArguments:\n  xla_allocate:\n    mesh: "cpu:2"\n'
    ).action_arguments

    def run(args):
        cache = FakeCache(synthetic(48, 8, seed=5))
        ssn = open_session(cache, parse_scheduler_conf(TIERS_YAML).tiers, args)
        action = get_action("xla_allocate")
        action.execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds), action.last_mesh_size

    sharded, mesh_n = run(action_args)
    assert mesh_n == 2
    single, mesh_1 = run({})
    assert mesh_1 == 1
    assert sharded == single and len(sharded) > 0


def test_sharded_action_pod_affinity_resume_parity():
    """The segmented pod-affinity hybrid under a mesh: the paused state is
    gathered to host, serial-stepped, and re-enters the *sharded* resume
    program — binds must still match the single-chip run."""
    from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
    from kube_batch_tpu.framework import close_session, get_action
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    def mk():
        anchor = build_pod(
            name="anchor",
            node_name="n0",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=1, memory="128Mi"),
            labels={"app": "db"},
        )
        pods, groups = [anchor], []
        for i in range(12):
            p = build_pod(
                name=f"p{i}",
                group_name=f"g{i}",
                req=build_resource_list(cpu=1, memory="256Mi"),
            )
            p.metadata.creation_timestamp = float(i)
            if i in (4, 9):  # two host-only tasks -> two pause/resume trips
                p.affinity = Affinity(
                    pod_affinity_required=[PodAffinityTerm(label_selector={"app": "db"})]
                )
            pg = build_pod_group(f"g{i}", min_member=1)
            pg.metadata.creation_timestamp = float(i)
            pods.append(p)
            groups.append(pg)
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=20))
            for i in range(4)
        ]
        return build_cluster(pods, nodes, groups, [build_queue("default")])

    def run(mesh_spec):
        cache = FakeCache(mk())
        ssn = open_session(
            cache,
            parse_scheduler_conf(TIERS_YAML).tiers,
            {"xla_allocate": {"mesh": mesh_spec}},
        )
        action = get_action("xla_allocate")
        action.execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds), action.last_mesh_size

    sharded, mesh_n = run("cpu:4")
    assert mesh_n == 4
    single, _ = run("off")
    assert sharded == single and len(sharded) == 12


def test_sharded_solve_10k_class_bucket():
    """Scale-proof (VERDICT r2 item 8): a 10k-task x 1k-node-class bucket
    under the reference's default conf (drf + proportion in the loop
    state), sharded 8 ways — GSPMD partitions meaningfully at this size
    (128 node columns per device) and must agree with the single-device
    solve assignment for assignment."""
    ssn = open_session(
        FakeCache(multi_queue(10_000, 1000)),
        parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers,
    )
    enc = encode_session(
        ssn.jobs,
        ssn.nodes,
        ssn.queues,
        dtype=np.float64,
        drf=ssn.plugins.get("drf"),
        proportion=ssn.plugins.get("proportion"),
    )
    arrays = dict(enc.arrays)
    arrays.update(
        w_least=np.float64(1), w_balanced=np.float64(1), w_aff=np.float64(1), w_podaff=np.float64(1)
    )
    single = solve_allocate(arrays, enable_drf=True, enable_proportion=True)
    sharded = sharded_solve_allocate(
        arrays, make_mesh(8), enable_drf=True, enable_proportion=True
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_kind), np.asarray(sharded.assigned_kind)
    )
    assert int(single.n_assigned) == int(sharded.n_assigned) == 10_000
