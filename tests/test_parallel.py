"""Multi-chip sharding tests on the 8-device virtual CPU mesh
(conftest forces xla_force_host_platform_device_count=8): the sharded
solve must agree exactly with the single-device solve, and the driver's
dryrun contract must hold."""

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import open_session
from kube_batch_tpu.models import multi_queue, synthetic
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.ops.kernels import solve_allocate
from kube_batch_tpu.parallel import make_mesh, sharded_solve_allocate
from kube_batch_tpu.testing import FakeCache

TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""


def encoded(cluster):
    ssn = open_session(FakeCache(cluster), parse_scheduler_conf(TIERS_YAML).tiers)
    enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64)
    arrays = dict(enc.arrays)
    arrays.update(w_least=np.float64(1), w_balanced=np.float64(1), w_aff=np.float64(1), w_podaff=np.float64(1))
    return enc, arrays


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_sharded_solve_matches_single_device(n_devices):
    enc, arrays = encoded(synthetic(120, 24, seed=3))
    single = solve_allocate(arrays)
    mesh = make_mesh(n_devices)
    sharded = sharded_solve_allocate(arrays, mesh)
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_kind), np.asarray(sharded.assigned_kind)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assign_pos), np.asarray(sharded.assign_pos)
    )
    assert int(single.n_assigned) == int(sharded.n_assigned) > 0


def test_sharded_solve_multi_queue():
    enc, arrays = encoded(multi_queue(96, 16, n_queues=3, tasks_per_job=6, seed=7))
    single = solve_allocate(arrays)
    sharded = sharded_solve_allocate(arrays, make_mesh(8))
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )


def test_dryrun_multichip_contract():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_contract():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert int(out.n_assigned) > 0


DEFAULT_TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def test_sharded_solve_10k_class_bucket():
    """Scale-proof (VERDICT r2 item 8): a 10k-task x 1k-node-class bucket
    under the reference's default conf (drf + proportion in the loop
    state), sharded 8 ways — GSPMD partitions meaningfully at this size
    (128 node columns per device) and must agree with the single-device
    solve assignment for assignment."""
    ssn = open_session(
        FakeCache(multi_queue(10_000, 1000)),
        parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers,
    )
    enc = encode_session(
        ssn.jobs,
        ssn.nodes,
        ssn.queues,
        dtype=np.float64,
        drf=ssn.plugins.get("drf"),
        proportion=ssn.plugins.get("proportion"),
    )
    arrays = dict(enc.arrays)
    arrays.update(
        w_least=np.float64(1), w_balanced=np.float64(1), w_aff=np.float64(1), w_podaff=np.float64(1)
    )
    single = solve_allocate(arrays, enable_drf=True, enable_proportion=True)
    sharded = sharded_solve_allocate(
        arrays, make_mesh(8), enable_drf=True, enable_proportion=True
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_node), np.asarray(sharded.assigned_node)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assigned_kind), np.asarray(sharded.assigned_kind)
    )
    assert int(single.n_assigned) == int(sharded.n_assigned) == 10_000
